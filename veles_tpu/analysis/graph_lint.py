"""Static rules over a constructed Workflow's control/data graph.

The reference Veles guarded gate deadlocks and dangling links at *runtime*
(locks + deadlock watchdog, SURVEY §5); our single-threaded queue scheduler
deleted that surface, which moved the same failure modes to
graph-construction time — where they are statically decidable.  Every rule
here runs on a workflow that has been *constructed but not initialized*:
no ``initialize()``, no XLA dispatch, no data touched.

Rule catalog (docs/static_analysis.md):

========  ========  =====================================================
VG001     error     control cycle with no loop closer (Repeater /
                    ``ignores_gate``) — the gates inside the cycle can
                    never all fire for the first iteration
VG002     warning   unit with control links but unreachable from
                    ``start_point`` (info when the unit has no control
                    links at all — a passive introspection handle)
VG003     error     gate deadlock: a unit waits on an unreachable
                    predecessor, or its ``gate_block`` is constant-true
VG004     error     dangling data link: ``link_attrs`` source unit is not
                    (or no longer) in the workflow
VG005     error     one-way data-link write hazard: unit code assigns an
                    attribute that is linked one-way (raises at runtime)
VG006     error     unsatisfiable ``demand()``: not linked, unset, and no
                    unit code in the workflow ever assigns it
========  ========  =====================================================
"""

import ast
import inspect
import itertools
import textwrap

from veles_tpu.analysis.findings import ERROR, INFO, WARNING, Finding
from veles_tpu.mutable import Bool
from veles_tpu.units import Unit

#: gate slots excluded from the "named Bool" registry — a Bool that only
#: ever appears as a gate is anonymous to the rest of the program and
#: nothing can flip it at runtime
_GATE_SLOTS = ("gate_block", "gate_skip", "ignores_gate")

#: give up on truth-table enumeration beyond this many mutable leaves
#: (2^10 evaluations) and conservatively assume the gate can open
_MAX_ENUM_LEAVES = 10


def lint_graph(wf):
    """Run every graph rule over ``wf``; returns a list of Findings."""
    findings = []
    findings.extend(_rule_cycles(wf))
    findings.extend(_rule_unreachable(wf))
    findings.extend(_rule_gate_deadlock(wf))
    findings.extend(_rule_dangling_links(wf))
    findings.extend(_rule_one_way_writes(wf))
    findings.extend(_rule_demands(wf))
    return findings


# --------------------------------------------------------------- gate truth
def _bool_registry(wf):
    """ids of every Bool the program can plausibly flip at runtime: Bools
    held as non-gate attributes of the workflow or any unit, plus Bools
    captured in closure cells of unit methods (the local completion-flag
    idiom).  A gate whose leaves all fall OUTSIDE this registry is a
    construction-time constant."""
    reg = set()
    objs = [wf] + list(wf.units)
    for obj in objs:
        for k, v in vars(obj).items():
            if k in _GATE_SLOTS:
                continue
            if isinstance(v, Bool):
                reg.add(id(v))
    for obj in objs:
        for cls in type(obj).__mro__:
            for v in vars(cls).values():
                fn = getattr(v, "__func__", v)
                code = getattr(fn, "__code__", None)
                if code is None:
                    continue
                for cell in getattr(fn, "__closure__", None) or ():
                    try:
                        if isinstance(cell.cell_contents, Bool):
                            reg.add(id(cell.cell_contents))
                    except ValueError:
                        pass  # empty cell
                # module-level flag idiom: a method flipping a Bool held
                # as a global of its defining module
                mod_globals = getattr(fn, "__globals__", None) or {}
                for name in code.co_names:
                    if isinstance(mod_globals.get(name), Bool):
                        reg.add(id(mod_globals[name]))
    # cross-unit gate surgery idiom: some unit's RUNTIME code (outside
    # __init__) writes a gate slot (`x.gate_block <<= ...` /
    # `x.gate_block.set(...)`).  The AST can't resolve WHICH unit's gate,
    # so every gate Bool becomes flippable — conservative: the
    # constant-true rule then stays silent rather than flag a gate the
    # program provably manipulates at runtime.
    if any(set(_GATE_SLOTS) & _scan_writes(obj).runtime_writes
           for obj in objs):
        for obj in objs:
            for slot in _GATE_SLOTS:
                gate = vars(obj).get(slot)
                if isinstance(gate, Bool):
                    for leaf in gate.leaves():
                        reg.add(id(leaf))
    return reg


def _statically_true(gate, registry):
    """True when ``gate`` evaluates True now and no runtime assignment can
    make it False: a plain truthy non-Bool, an anonymous value Bool, or a
    derived Bool that stays True under every assignment of its mutable
    (registry-listed) leaves."""
    if not isinstance(gate, Bool):
        return bool(gate)
    if not bool(gate):
        return False
    leaves = gate.leaves()
    if gate.derived and not leaves and not gate.operands:
        # bare-lambda Bool with no structural metadata: opaque — assume
        # the program knows how to open it
        return False
    mutable = [l for l in leaves if id(l) in registry]
    if not mutable:
        return True
    if len(mutable) > _MAX_ENUM_LEAVES:
        return False
    saved = [l._value for l in mutable]
    try:
        for assignment in itertools.product((False, True),
                                            repeat=len(mutable)):
            for leaf, value in zip(mutable, assignment):
                leaf._value = value
            if not bool(gate):
                return False
        return True  # tautology over every mutable leaf
    finally:
        for leaf, value in zip(mutable, saved):
            leaf._value = value


def _gate_expr(gate):
    return gate.expression() if isinstance(gate, Bool) else repr(gate)


# -------------------------------------------------------------- VG001 cycles
def _rule_cycles(wf):
    """Tarjan SCC over control links; a non-trivial SCC with no statically
    open ``ignores_gate`` member deadlocks on its own first iteration."""
    nodes = list(wf.units)
    node_set = set(nodes)
    index = {}
    lowlink = {}
    on_stack = set()
    stack = []
    sccs = []
    counter = itertools.count()

    def strongconnect(v):
        # iterative Tarjan: unit graphs can be deep chains
        work = [(v, iter([d for d in v.links_to if d in node_set]))]
        index[v] = lowlink[v] = next(counter)
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = lowlink[w] = next(counter)
                    stack.append(w)
                    on_stack.add(w)
                    work.append(
                        (w, iter([d for d in w.links_to if d in node_set])))
                    advanced = True
                    break
                elif w in on_stack:
                    lowlink[node] = min(lowlink[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w is node:
                        break
                sccs.append(scc)

    for v in nodes:
        if v not in index:
            strongconnect(v)

    findings = []
    for scc in sccs:
        trivial = len(scc) == 1 and scc[0] not in scc[0].links_to
        if trivial:
            continue
        if any(bool(u.ignores_gate) for u in scc):
            continue  # a Repeater-style closer re-enters the loop
        names = ", ".join(sorted(u.name for u in scc))
        findings.append(Finding(
            "VG001", ERROR, names,
            "control cycle with no loop closer — every unit in the cycle "
            "waits for all predecessors, so none can fire first",
            hint="route the loop through a plumbing.Repeater (or set "
                 "ignores_gate on the unit that re-enters the cycle)"))
    return findings


# -------------------------------------------------------- VG002 reachability
def _rule_unreachable(wf):
    reachable = set(wf.control_reachable())
    findings = []
    for u in wf.units:
        if u in reachable:
            continue
        if not u.links_from and not u.links_to:
            findings.append(Finding(
                "VG002", INFO, u.name,
                "passive unit: no control links at all (never scheduled; "
                "fine for introspection handles)",
                hint="link_from(...) it if it was meant to run"))
        else:
            findings.append(Finding(
                "VG002", WARNING, u.name,
                "has control links but is unreachable from start_point — "
                "it will never run",
                hint="connect it (transitively) to "
                     "workflow.start_point via link_from"))
    return findings


# ------------------------------------------------------- VG003 gate deadlock
def _rule_gate_deadlock(wf):
    reachable = set(wf.control_reachable())
    registry = _bool_registry(wf)
    findings = []
    for u in wf.units:
        if u in reachable and _statically_true(u.gate_block, registry):
            findings.append(Finding(
                "VG003", ERROR, u.name,
                "gate_block is constant-true (%s): the unit never runs "
                "and never propagates — every non-ignores_gate successor "
                "deadlocks" % _gate_expr(u.gate_block),
                hint="gate on a Bool some unit actually flips (e.g. "
                     "decision.complete), or drop the gate"))
        if u not in reachable or bool(u.ignores_gate):
            continue
        dead = sorted(p.name for p in u.links_from if p not in reachable)
        if dead:
            findings.append(Finding(
                "VG003", ERROR, u.name,
                "waits on unreachable predecessor(s) %s — its gate can "
                "never fully open" % ", ".join(dead),
                hint="make the predecessor reachable from start_point, "
                     "unlink it, or set ignores_gate on this unit"))
    return findings


# ------------------------------------------------------ VG004 dangling links
def _rule_dangling_links(wf):
    members = set(wf.units) | {wf}
    findings = []
    for u in [wf] + list(wf.units):
        for mine, (src, theirs, _two_way) in u.linked_attrs.items():
            if src in members:
                continue
            findings.append(Finding(
                "VG004", ERROR, u.name,
                "data link %r reads %s.%s, but that unit is not in the "
                "workflow (del_ref'd, unlinked, or never added)"
                % (mine, getattr(src, "name", src), theirs),
                hint="re-link the attribute to a live unit, or "
                     "unlink_attrs(%r) if the link is obsolete" % mine))
    return findings


# ------------------------------------------------------------ source scanning
#: mro scanning stops at the framework core: its only non-construction
#: gate write is Workflow.change_unit's splice, which must not read as
#: "user code flips gates at runtime"
_FRAMEWORK_CORE = ("veles_tpu.units", "veles_tpu.workflow",
                   "veles_tpu.plumbing")


def _class_sources(unit):
    """(class, dedented source) for every user class in the unit's mro —
    framework bases (Unit/Container/Workflow and above) are trusted."""
    out = []
    for cls in type(unit).__mro__:
        if cls in (Unit,) or issubclass(Unit, cls) \
                or cls.__module__ in _FRAMEWORK_CORE:
            break
        try:
            out.append((cls, textwrap.dedent(inspect.getsource(cls))))
        except (OSError, TypeError):
            pass  # dynamically created class: no source to scan
    return out


class _AttrWrites(ast.NodeVisitor):
    """Collect attribute names assigned anywhere (``x.attr = ...`` and
    augmented forms), the subset assigned specifically on ``self``
    outside ``__init__``, attribute names written on ANY object outside
    ``__init__`` (including ``x.attr <<= ...`` and ``x.attr.set(...)`` —
    the runtime gate-flip idioms), and whether ``setattr`` is called."""

    def __init__(self):
        self.all_writes = set()
        self.self_writes = {}  # attr -> (method, lineno)
        self.runtime_writes = set()
        self.uses_setattr = False
        self._method = None

    def visit_FunctionDef(self, node):
        prev, self._method = self._method, node.name
        self.generic_visit(node)
        self._method = prev

    visit_AsyncFunctionDef = visit_FunctionDef

    @property
    def _in_ctor(self):
        return self._method in ("__init__", "__new__")

    def _target(self, t):
        if isinstance(t, ast.Attribute):
            self.all_writes.add(t.attr)
            if not self._in_ctor:
                self.runtime_writes.add(t.attr)
                if isinstance(t.value, ast.Name) and t.value.id == "self":
                    self.self_writes.setdefault(
                        t.attr, (self._method, t.lineno))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    def visit_With(self, node):
        for item in node.items:
            if item.optional_vars is not None:
                self._target(item.optional_vars)
        self.generic_visit(node)

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        if isinstance(node.func, ast.Name) and node.func.id == "setattr":
            self.uses_setattr = True
        if not self._in_ctor and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "set" \
                and isinstance(node.func.value, ast.Attribute):
            # x.attr.set(...) — Bool mutation through an attribute
            self.runtime_writes.add(node.func.value.attr)
        self.generic_visit(node)


def _scan_writes(unit, _cache={}):
    """Merged _AttrWrites over every user class in the unit's mro."""
    merged = _AttrWrites()
    for cls, src in _class_sources(unit):
        if cls not in _cache:
            scanner = _AttrWrites()
            try:
                scanner.visit(ast.parse(src))
            except SyntaxError:
                pass
            _cache[cls] = scanner
        s = _cache[cls]
        merged.all_writes |= s.all_writes
        merged.runtime_writes |= s.runtime_writes
        merged.uses_setattr |= s.uses_setattr
        for attr, where in s.self_writes.items():
            merged.self_writes.setdefault(attr, where)
    return merged


# ----------------------------------------------------- VG005 one-way writes
def _rule_one_way_writes(wf):
    findings = []
    for u in wf.units:
        one_way = [mine for mine, (_s, _t, two_way)
                   in u.linked_attrs.items() if not two_way]
        if not one_way:
            continue
        writes = _scan_writes(u).self_writes
        for mine in one_way:
            if mine not in writes:
                continue
            method, lineno = writes[mine]
            src, theirs, _ = u.linked_attrs[mine]
            findings.append(Finding(
                "VG005", ERROR, u.name,
                "%s.%s() assigns self.%s (line +%d), but the attribute "
                "is linked ONE-WAY from %s.%s — the write raises "
                "AttributeError at runtime"
                % (type(u).__name__, method, mine, lineno,
                   getattr(src, "name", src), theirs),
                hint="link with two_way=True if the unit must write "
                     "back, or write to a differently named attribute"))
    return findings


# ---------------------------------------------------------- VG006 demands
def _rule_demands(wf):
    findings = []
    # the workflow is itself a Unit: its initialize() may assign demanded
    # attributes (and it can hold demands of its own) — scan it too
    all_units = [wf] + list(wf.units)
    # union of attribute names any unit code in this workflow ever
    # assigns — computed lazily, only when some demand is actually open
    assigned = None
    uses_setattr = False
    for u in all_units:
        open_demands = [n for n in u._demanded_
                        if n not in u._linked_attrs_
                        and getattr(u, n, None) is None]
        if not open_demands:
            continue
        if assigned is None:
            assigned = set()
            for other in all_units:
                scan = _scan_writes(other)
                assigned |= scan.all_writes
                uses_setattr |= scan.uses_setattr
        if uses_setattr:
            return findings  # dynamic assignment: cannot decide statically
        for n in open_demands:
            if n in assigned:
                continue  # some unit's initialize()/run() may provide it
            findings.append(Finding(
                "VG006", ERROR, u.name,
                "demand(%r) can never be satisfied: no data link, the "
                "attribute is unset, and no unit code in the workflow "
                "assigns it — initialize() would deadlock requeueing" % n,
                hint="link_attrs the producer, set the attribute before "
                     "initialize(), or drop the demand"))
    return findings
