"""Sharding & memory auditor: static collective/HBM lint of the staged
step under a device mesh.

The distribution story is "annotate shardings, let GSPMD insert
collectives" (parallel/sharding.py) — which makes the expensive failure
modes invisible until chip time: an accidental all-gather of full
parameters every step, a tensor silently replicated because a dim didn't
divide its axis, an OOM that only shows up on real silicon.  All of them
are statically decidable BEFORE any chip is touched: the step is lowered
under the mesh on abstract ``jax.ShapeDtypeStruct`` inputs
(``jax.jit(...).lower(...)`` — no data, no dispatch, works on the
CPU-device mesh lint runs on), the post-SPMD module text is scanned for
the collectives XLA actually inserted, and the jaxpr is walked for
upcasts and the activation high-water mark.  Bytes are priced with the
SAME table the roofline cost model uses (``veles_tpu.ops.flops.
DTYPE_BYTES`` — tools/cost_model.py), so lint, bench and prediction
cannot silently diverge.  This is the "analyze before you run"
discipline of TVM's static cost models and CLBlast's offline-tuned DB
(PAPERS.md) applied to the sharded training step.

Rule catalog (docs/static_analysis.md):

========  ========  =====================================================
VS200     warning   per-device all-gather/all-reduce volume per step
                    exceeds the per-device minibatch bytes — the step
                    ships ~the model over ICI every iteration
VS201     warning   tensor silently replicated: a sharding rule wanted
                    an axis but the dim didn't divide (recorded by
                    parallel/sharding.py on MeshConfig.sharding_fallbacks)
VS202     warning   FSDP mode but gradients ride a full ``psum``
                    (all-reduce) instead of the expected reduce-scatter —
                    full gradient materialized per device
VS203     warning   bf16 parameter upcast to f32 inside the step —
                    doubles the parameter HBM traffic
VM300     info      static per-device peak-HBM estimate (params + opt
          /error    slots + data + activation high-water) — error when
                    it exceeds the per-device capacity (predicted OOM)
VM301     warning   donation miss: a carry buffer (params/opt state) is
                    not donated, or XLA dropped the declared donation —
                    the carry lives twice
========  ========  =====================================================
"""

import re

import jax
import jax.numpy as jnp

from veles_tpu.analysis.findings import ERROR, INFO, WARNING, Finding
from veles_tpu.analysis.staging import _sub_jaxprs, iter_primitives
from veles_tpu.ops.flops import dtype_nbytes, shape_nbytes

#: default per-device HBM capacity, GiB (v5e — the same chip the
#: tools/cost_model.py roofline is calibrated for)
DEFAULT_HBM_GIB = 16.0

#: result-shape token in HLO text, e.g. ``f32[128,256]``
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
#: a collective instruction definition: ``%x = <shapes> all-gather(...``
#: (``-start`` async halves count once; ``-done`` carries no new bytes)
_COLLECTIVE_RE = re.compile(
    r"=\s+(.+?)\s+(all-gather|all-reduce|reduce-scatter|all-to-all|"
    r"collective-permute)(-start)?\(")


def _token_bytes(dtype_tok, dims_tok):
    n = 1
    for d in dims_tok.split(","):
        if d:
            n *= int(d)
    try:
        return n * dtype_nbytes(dtype_tok)
    except TypeError:
        return 0          # opaque token (token/tuple glue) — no bytes


def collective_stats(hlo_text):
    """``{op: {"count", "bytes"}}`` parsed from post-SPMD optimized
    module text — per-device result bytes of every collective XLA
    inserted (all-gather counts the gathered size, i.e. received
    traffic)."""
    stats = {}
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        shapes, op, is_start = m.group(1), m.group(2), m.group(3)
        tokens = _SHAPE_RE.findall(shapes)
        if is_start and len(tokens) > 1:
            # async def lines yield a (operand, result) tuple shape —
            # only the result carries the traffic
            tokens = tokens[-1:]
        nbytes = sum(_token_bytes(d, s) for d, s in tokens)
        rec = stats.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += nbytes
    return stats


def _aval_bytes(aval):
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return shape_nbytes(shape, dtype)
    except TypeError:
        return 0


def activation_highwater(jaxpr):
    """Peak live *intermediate* bytes from a linear liveness walk of the
    equations (global, unpartitioned sizes; the jaxpr's own outputs are
    excluded — they are accounted as resident carries/outputs, not
    transient activations).  Sub-jaxprs (pjit/scan/cond bodies) recurse:
    an eqn's footprint is the larger of its own outputs and its body's
    high-water."""
    out_ids = {id(v) for v in jaxpr.outvars}
    last_use = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            last_use[id(v)] = i
    live = {}
    cur = high = 0
    for i, eqn in enumerate(jaxpr.eqns):
        inner = 0
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                inner = max(inner, activation_highwater(sub))
        for v in eqn.outvars:
            if id(v) in out_ids or id(v) in live:
                continue
            b = _aval_bytes(getattr(v, "aval", None))
            live[id(v)] = b
            cur += b
        high = max(high, cur + inner)
        for v in list(eqn.invars) + list(eqn.outvars):
            if last_use.get(id(v), -1) <= i and id(v) in live:
                cur -= live.pop(id(v))
    return high


def _leaf_device_bytes(x):
    """Per-device resident bytes of one input leaf: its global bytes
    divided by how its NamedSharding splits it (``shard_shape``)."""
    shape = tuple(getattr(x, "shape", ()) or ())
    dtype = getattr(x, "dtype", None)
    if dtype is None:
        return 0
    sh = getattr(x, "sharding", None)
    if sh is not None and hasattr(sh, "shard_shape"):
        try:
            shape = sh.shard_shape(shape)
        except Exception:  # noqa: BLE001 — unsharded/abstract: global
            pass
    return shape_nbytes(shape, dtype)


def _abstract_args(args, mesh_cfg):
    """ShapeDtypeStruct mirror of ``args`` (concrete arrays or specs).
    Mesh-sharded leaves keep their NamedSharding; everything else is
    pinned replicated — an uncommitted single-device array mixed into a
    mesh computation would otherwise fail the abstract lowering."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    repl = NamedSharding(mesh_cfg.mesh, P())

    def leaf(x):
        sh = getattr(x, "sharding", None)
        if not isinstance(sh, NamedSharding):
            sh = repl
        return jax.ShapeDtypeStruct(tuple(jnp.shape(x)),
                                    jnp.result_type(x), sharding=sh)
    return tuple(jax.tree_util.tree_map(leaf, a) for a in args)


def _argnum_leaves(args, argnums):
    leaves = []
    for n in argnums:
        leaves.extend(jax.tree_util.tree_leaves(args[n]))
    return leaves


def _mib(nbytes):
    return nbytes / (1024.0 * 1024.0)


def estimate_peak_hbm(spec, _args=None, _closed=None, act_bytes=None):
    """VM300's static per-device peak-HBM estimate, as a component dict:
    ``params`` + ``opt`` + ``other_args`` (HBM-resident step inputs,
    each divided by its sharding; a buffer passed as several args — the
    autoencoder's targets ARE its data — counts once) + ``activations``
    + ``undonated`` (carry outputs that double because their input
    buffer was not donated); ``peak`` is the sum.

    ``activations``: ``act_bytes`` when given (the auditor passes XLA's
    own per-device ``temp_size_in_bytes`` from the compiled module —
    exact, includes data-parallel gradient replicas); otherwise the
    jaxpr liveness high-water over the global program divided by the
    data-axis size — a heuristic that treats every intermediate as
    batch-sharded, which UNDERCOUNTS replicated param-sized gradients
    under pure data parallelism.  Purely static either way: traces
    abstractly, allocates nothing."""
    mc = spec["mesh_config"]
    args = _args if _args is not None else _abstract_args(spec["args"],
                                                          mc)
    closed = _closed
    carry = tuple(spec.get("carry_argnums", ()))
    donate = tuple(spec.get("donate_argnums", ()))
    data_size = max(getattr(mc, "data_size", 1), 1)
    params_bytes = sum(_leaf_device_bytes(x) for x in _argnum_leaves(
        args, tuple(spec.get("params_argnums", ()))))
    opt_bytes = sum(_leaf_device_bytes(x) for x in _argnum_leaves(
        args, tuple(spec.get("opt_argnums", ()))))
    # one physical buffer may arrive as several args (aliasing is only
    # visible on the ORIGINAL leaves — the abstract mirrors are fresh
    # objects, so pair them up positionally)
    orig = [x for a in spec["args"]
            for x in jax.tree_util.tree_leaves(a)]
    mirror = [x for a in args for x in jax.tree_util.tree_leaves(a)]
    seen = set()
    args_bytes = 0
    for o, m in zip(orig, mirror):
        if id(o) in seen:
            continue
        seen.add(id(o))
        args_bytes += _leaf_device_bytes(m)
    if act_bytes is None:
        if closed is None:
            closed = jax.make_jaxpr(spec["fn"])(*args)
        act_bytes = activation_highwater(closed.jaxpr) // data_size
    undonated = sum(_leaf_device_bytes(x) for x in _argnum_leaves(
        args, [n for n in carry if n not in donate]))
    return {"peak": args_bytes + act_bytes + undonated,
            "params": params_bytes, "opt": opt_bytes,
            "other_args": args_bytes - params_bytes - opt_bytes,
            "activations": act_bytes, "undonated": undonated}


def audit_sharded_step(spec, hbm_gib=None):
    """Audit one staged step under its mesh; returns VS2xx/VM3xx Findings.

    ``spec`` (the shape ``StagedTrainer.lint_sharding_spec()`` returns):

    ``fn``              the step — a ``jax.jit`` object or plain callable
    ``args``            positional args: concrete arrays and/or
                        ``jax.ShapeDtypeStruct`` specs (never executed)
    ``mesh_config``     parallel.MeshConfig (axes + fsdp flag +
                        recorded sharding fallbacks)
    ``donate_argnums``  argnums the step donates
    ``carry_argnums``   argnums whose outputs replace them next iteration
    ``params_argnums``  argnums holding the parameter pytree
    ``opt_argnums``     argnums holding the optimizer state pytree
    ``minibatch_bytes`` global bytes one minibatch moves per step
    ``name``            display name for findings

    Everything here is static: the step is lowered and compiled for the
    mesh on ABSTRACT inputs — no computation is dispatched and no device
    array is created (asserted in tests/test_sharding_audit.py)."""
    fn = spec["fn"]
    mc = spec["mesh_config"]
    name = spec.get("name", "step")
    donate = tuple(spec.get("donate_argnums", ()))
    carry = tuple(spec.get("carry_argnums", ()))
    params_argnums = tuple(spec.get("params_argnums", ()))
    opt_argnums = tuple(spec.get("opt_argnums", ()))
    fsdp = bool(getattr(mc, "fsdp", False))
    capacity = int((hbm_gib or spec.get("hbm_gib") or DEFAULT_HBM_GIB)
                   * 1024 ** 3)
    findings = []

    args = _abstract_args(spec["args"], mc)

    # ---- VS201: silent-replication fallbacks recorded by the sharding
    # rules at shard_params time (parallel/sharding.py)
    for fb in getattr(mc, "sharding_fallbacks", ()):
        where = fb["layer"] or "<unnamed>"
        if fb["param"]:
            where += "." + fb["param"]
        shape = ("%r" % (fb["shape"],) if fb["shape"] is not None
                 else "")
        if fb.get("replicated", True):
            findings.append(Finding(
                "VS201", WARNING, name,
                "%s %s silently replicated: %s"
                % (where, shape, fb["reason"]),
                hint="pad the dim to a multiple of the axis, pick a "
                     "mesh whose axis divides it, or override "
                     "param_partition_specs for this layer"))
        else:
            # still sharded on another axis — only the extra axis was
            # missed (every 1-D bias under fsdp): informational, so a
            # clean fsdp config passes `--fail-on warning`
            findings.append(Finding(
                "VS201", INFO, name,
                "%s %s kept its sharding but missed an extra axis: %s"
                % (where, shape, fb["reason"])))

    # ---- abstract lowering under the mesh (no devices touched)
    try:
        lowerable = fn if hasattr(fn, "lower") else jax.jit(
            fn, donate_argnums=donate)
        lowered = lowerable.lower(*args)
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — any lowering failure is the finding
        findings.append(Finding(
            "VJ100", ERROR, name,
            "staged step failed to lower under the mesh %s: %s: %s"
            % (dict(mc.mesh.shape), type(e).__name__, e),
            hint="the step must trace abstractly with its sharding "
                 "annotations — no data-dependent python control flow"))
        return findings

    # ---- VS203: bf16 params upcast to f32 inside the step
    bf16 = jnp.result_type(jnp.bfloat16)
    f32 = jnp.result_type(jnp.float32)
    bf16_param_shapes = set()
    for leaf in _argnum_leaves(args, params_argnums):
        if jnp.result_type(leaf.dtype) == bf16:
            bf16_param_shapes.add(tuple(leaf.shape))
    if bf16_param_shapes:
        upcast = set()
        for prim_name, eqn in iter_primitives(closed.jaxpr):
            if prim_name != "convert_element_type":
                continue
            if jnp.result_type(eqn.params.get("new_dtype")) != f32:
                continue
            src = eqn.invars[0].aval
            if (getattr(src, "dtype", None) == bf16
                    and tuple(getattr(src, "shape", ())) in
                    bf16_param_shapes):
                upcast.add(tuple(src.shape))
        for shape in sorted(upcast):
            findings.append(Finding(
                "VS203", WARNING, name,
                "bf16 parameter-shaped tensor %r upcast to f32 inside "
                "the step — the cast doubles its HBM traffic every "
                "iteration" % (shape,),
                hint="keep the compute dtype bf16 (ops/policy), or "
                     "store the master copy in f32 and cast ONCE "
                     "outside the step"))

    # ---- VM301(a): carry args not donated — params + opt state live
    # twice while the step runs
    missing = [n for n in carry if n not in donate]
    if missing:
        names = {n: "params" for n in params_argnums}
        names.update({n: "optimizer state" for n in opt_argnums})
        what = ", ".join("arg %d (%s)" % (n, names.get(n, "carry"))
                         for n in missing)
        findings.append(Finding(
            "VM301", WARNING, name,
            "carry buffer not donated: %s — input and output copies "
            "are both live across the step, doubling their HBM"
            % what,
            hint="jit with donate_argnums covering every carry arg "
                 "(the output reuses the input buffer)"))

    # ---- compile for the mesh: the post-SPMD module text holds the
    # collectives GSPMD actually inserted, and XLA's own buffer stats
    compiled = None
    try:
        compiled = lowered.compile()
    except Exception as e:  # noqa: BLE001 — collective rules degrade gracefully
        findings.append(Finding(
            "VM300", INFO, name,
            "could not compile the lowered step for collective/HBM "
            "inspection (%s: %s) — VS200/VS202 skipped, VM300 is "
            "jaxpr-only" % (type(e).__name__, e)))

    data_size = max(getattr(mc, "data_size", 1), 1)
    mb_bytes = int(spec.get("minibatch_bytes") or 0)
    mb_per_device = mb_bytes // data_size if mb_bytes else 0
    param_dev_bytes = sum(_leaf_device_bytes(x) for x in
                          _argnum_leaves(args, params_argnums))

    mem_stats = None
    if compiled is not None:
        try:
            mem_stats = compiled.memory_analysis()
        except Exception:  # noqa: BLE001 — backend without buffer stats
            mem_stats = None
        stats = collective_stats(compiled.as_text())
        ag = stats.get("all-gather", {"count": 0, "bytes": 0})
        ar = stats.get("all-reduce", {"count": 0, "bytes": 0})
        rs = stats.get("reduce-scatter", {"count": 0, "bytes": 0})
        heavy = ag["bytes"] + ar["bytes"]

        # VS200: the step ships ~the model over ICI every iteration.
        # Threshold: 2x the per-device minibatch (the in-step sharded
        # gather legitimately moves one minibatch, parallel/sharding.py
        # make_sharded_gather) AND at least half the per-device params
        # (so tiny-model noise never fires).
        threshold = max(2 * mb_per_device, param_dev_bytes // 2, 1)
        if heavy > threshold:
            findings.append(Finding(
                "VS200", WARNING, name,
                "heavy collectives per step: %.2f MiB/device "
                "all-gather (%d) + %.2f MiB/device all-reduce (%d) "
                "exceeds %.2f MiB/device minibatch — the step ships "
                "~the model over ICI every iteration"
                % (_mib(ag["bytes"]), ag["count"], _mib(ar["bytes"]),
                   ar["count"], _mib(mb_per_device)),
                hint="check for a tensor GSPMD gathers back because "
                     "its sharding was lost (VS201 fallbacks), raise "
                     "the per-step batch / grad accumulation, or "
                     "shard the offender explicitly"))

        # VS202: fsdp gradients should REDUCE-SCATTER into the param
        # shards; a param-sized all-reduce means the full gradient is
        # materialized (and summed) on every device — ZeRO-3's memory
        # win silently lost.
        if fsdp and param_dev_bytes and \
                ar["bytes"] >= param_dev_bytes and \
                rs["bytes"] < param_dev_bytes:
            findings.append(Finding(
                "VS202", WARNING, name,
                "fsdp step all-reduces %.2f MiB/device of gradients "
                "but reduce-scatters only %.2f MiB (param shard: "
                "%.2f MiB) — expected reduce-scatter onto the "
                "parameter shards (ZeRO-3), got a full psum"
                % (_mib(ar["bytes"]), _mib(rs["bytes"]),
                   _mib(param_dev_bytes)),
                hint="pin the gradient/update out_shardings to the "
                     "fsdp param specs so GSPMD scatters the "
                     "reduction, and check VS201 for params whose "
                     "fsdp sharding fell back to replication"))

        # VM301(b): donation declared but dropped by XLA — the aliased
        # bytes should cover the donated carries
        if donate and mem_stats is not None:
            donated_bytes = sum(_leaf_device_bytes(x) for x in
                                _argnum_leaves(args, donate))
            alias = getattr(mem_stats, "alias_size_in_bytes", None)
            if alias is not None and donated_bytes and \
                    alias < donated_bytes // 2:
                findings.append(Finding(
                    "VM301", WARNING, name,
                    "donation declared for %.2f MiB/device of carry "
                    "buffers but XLA aliased only %.2f MiB — the "
                    "donated inputs are still copied"
                    % (_mib(donated_bytes), _mib(alias)),
                    hint="donated inputs must match their outputs in "
                         "shape/dtype/sharding (out_shardings pinned "
                         "to the input shardings)"))

    # ---- VM300: static per-device peak-HBM estimate (estimate_peak_hbm
    # for the accounting; XLA's own per-device temp bytes when the
    # compile succeeded — exact, includes replicated DP gradients —
    # else the jaxpr liveness heuristic)
    act_override = getattr(mem_stats, "temp_size_in_bytes", None)
    est = estimate_peak_hbm(spec, _args=args, _closed=closed,
                            act_bytes=act_override)
    peak = est["peak"]
    detail = ("params %.2f + opt %.2f + other args %.2f + activations "
              "%.2f + undonated carries %.2f MiB/device"
              % (_mib(est["params"]), _mib(est["opt"]),
                 _mib(est["other_args"]),
                 _mib(est["activations"]), _mib(est["undonated"])))
    if peak > capacity:
        findings.append(Finding(
            "VM300", ERROR, name,
            "predicted OOM: estimated peak %.2f MiB/device exceeds the "
            "%.1f GiB/device capacity (%s)"
            % (_mib(peak), capacity / 1024 ** 3, detail),
            hint="shard more (fsdp / bigger mesh), remat activations, "
                 "adafactor the optimizer slots, or shrink the batch"))
    elif peak > 0.9 * capacity:
        findings.append(Finding(
            "VM300", WARNING, name,
            "estimated peak %.2f MiB/device is within 10%% of the "
            "%.1f GiB/device capacity (%s)"
            % (_mib(peak), capacity / 1024 ** 3, detail)))
    else:
        findings.append(Finding(
            "VM300", INFO, name,
            "estimated peak %.2f MiB/device of %.1f GiB/device "
            "capacity (%s)" % (_mib(peak), capacity / 1024 ** 3,
                               detail)))
    return findings
