"""Performance target-contract lint (VL12xx).

The VC954 lesson applied to performance numbers: a target that nothing
measures is a contract violation exactly like a metric that nothing
emits.  The ledger (telemetry.ledger) made targets declarative — the
:data:`~veles_tpu.telemetry.ledger.TARGETS` registry is the
declaration, ledger records are the measurements — so the two sides
are cross-checkable:

========  =======  ==========================================
rule      severity  meaning
========  =======  ==========================================
VL1200    warning  target declared in the registry but never
                   measured: no ledger record answers it (the
                   "pre-registered but the TPU never answered"
                   state — ROADMAP item 1's failure mode)
VL1201    error    measurement references an unknown target: a
                   ledger record's ``target.id`` names no
                   registry entry (stale rename, or a target
                   deleted without migrating its history)
VL1202    error    conflicting target declaration: a registry
                   metric declared twice (the tuple is the one
                   source of truth — duplicates mean two bars
                   for one number)
VL1203    warning  polarity conflict: a record's ``better``
                   disagrees with its declared target's — the
                   sentinel would band one side while the gate
                   judges the other
========  =======  ==========================================

Pure data audit (no AST, no jax): it reads one ledger file and the
in-process registry, so it runs under ``veles-tpu-lint --perf``, the
``veles-tpu-perf gate``, and CI's perf-ledger job alike.  Rule
catalog: docs/static_analysis.md."""

from veles_tpu.analysis.findings import (ERROR, WARNING, Finding,
                                         sort_findings)

#: the full VL12xx contract family, in catalog order (the sentinel's
#: runtime verdicts VL1210/VL1211 live in telemetry.perfcli)
RULES = ("VL1200", "VL1201", "VL1202", "VL1203")


def lint_perf(ledger_path=None, targets=None, records=None):
    """Audit the declared-target vs measured-record contract.  Reads
    the process-default ledger unless ``ledger_path`` (or an explicit
    ``records`` list, for tests) is given.  Returns sorted
    Findings."""
    from veles_tpu.telemetry import ledger as led
    if targets is None:
        targets = led.TARGETS
    if records is None:
        book = (led.PerfLedger(ledger_path) if ledger_path
                else led.default())
        records = book.records()
    findings = []
    seen = {}
    for t in targets:
        if t.metric in seen and seen[t.metric] != t:
            findings.append(Finding(
                "VL1202", ERROR, t.metric,
                "target %r declared twice with conflicting goals "
                "(%r vs %r)" % (t.metric, seen[t.metric].goal,
                                t.goal),
                "keep exactly one Target per metric in "
                "telemetry.ledger.TARGETS"))
        seen[t.metric] = t
    by_metric = {t.metric: t for t in targets}
    measured = set()
    flagged_orphans = set()
    flagged_polarity = set()
    for rec in records:
        metric = rec.get("metric")
        measured.add(metric)
        tgt = rec.get("target") or None
        if isinstance(tgt, dict):
            tid = tgt.get("id", metric)
            if tid not in by_metric and tid not in flagged_orphans:
                flagged_orphans.add(tid)
                findings.append(Finding(
                    "VL1201", ERROR, str(metric),
                    "measurement carries target id %r that no "
                    "registry entry declares" % (tid,),
                    "register the target in "
                    "telemetry.ledger.TARGETS or drop the stale "
                    "reference when migrating history"))
            decl = by_metric.get(tid)
            if decl is not None and rec.get("better") \
                    and rec["better"] != decl.better \
                    and metric not in flagged_polarity:
                flagged_polarity.add(metric)
                findings.append(Finding(
                    "VL1203", WARNING, str(metric),
                    "record polarity %r disagrees with its "
                    "declared target's %r"
                    % (rec["better"], decl.better),
                    "fix the appender's better= (the sentinel "
                    "bands the record's polarity, the gate "
                    "judges the target's)"))
    for t in targets:
        if t.metric not in measured:
            findings.append(Finding(
                "VL1200", WARNING, t.metric,
                "target declared (%s %s %s, %s) but never "
                "measured: no ledger record answers it"
                % (t.metric, "<=" if t.better == "lower" else ">=",
                   t.goal, t.source),
                "run the measuring phase (%s) on the next TPU "
                "window — ROADMAP item 1" % (t.source,)))
    return sort_findings(findings)
