"""InputJoiner — concatenate several producer outputs into one tensor
(ref veles/input_joiner.py:49 + the generated concat kernel ocl/join.jcl;
on TPU the concat is one jnp op XLA fuses into the consumer)."""

import numpy as np

from veles_tpu.units import Unit


class InputJoiner(Unit):
    """Joins N inputs along the flattened feature axis.

    Producers are declared with ``link_input(unit, "attr")``; at run time
    each input is fetched, flattened per sample, and concatenated into
    ``self.output``.  All inputs must share the leading (sample) dimension.
    """

    def __init__(self, workflow, **kwargs):
        super(InputJoiner, self).__init__(workflow, **kwargs)
        self._inputs = []     # list of (producer_unit, attr_name)
        self.output = None
        self.output_sample_size = None

    def link_input(self, unit, attr="output"):
        self._inputs.append((unit, attr))
        return self

    def initialize(self, **kwargs):
        if not self._inputs:
            raise ValueError("InputJoiner has no inputs; call link_input()")

    def run(self):
        arrays = []
        for unit, attr in self._inputs:
            a = np.asarray(getattr(unit, attr))
            arrays.append(a.reshape(a.shape[0], -1))
        n = arrays[0].shape[0]
        for (unit, attr), a in zip(self._inputs, arrays):
            if a.shape[0] != n:
                raise ValueError(
                    "InputJoiner: %s.%s has %d samples, expected %d"
                    % (unit, attr, a.shape[0], n))
        self.output = np.concatenate(arrays, axis=1)
        self.output_sample_size = self.output.shape[1]
