"""Numpy-aware JSON encoding (ref veles/json_encoders.py)."""

import json

import numpy as np


class NumpyJSONEncoder(json.JSONEncoder):
    """Encodes numpy scalars/arrays (and jax arrays, via __array__) as
    plain JSON numbers/lists."""

    def default(self, o):
        if isinstance(o, np.integer):
            return int(o)
        if isinstance(o, np.floating):
            return float(o)
        if isinstance(o, np.bool_):
            return bool(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
        if hasattr(o, "__array__"):
            return np.asarray(o).tolist()
        return super(NumpyJSONEncoder, self).default(o)


def dumps(obj, **kwargs):
    kwargs.setdefault("cls", NumpyJSONEncoder)
    return json.dumps(obj, **kwargs)


def dump(obj, fp, **kwargs):
    kwargs.setdefault("cls", NumpyJSONEncoder)
    return json.dump(obj, fp, **kwargs)
