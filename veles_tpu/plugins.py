"""Plugin discovery (ref veles/__init__.py:294-307 — any installed package
shipping a ``.veles`` marker file is auto-imported so its units register).

Here a plugin is either (a) a package with a ``.veles_tpu`` marker file in
its directory, or (b) an entry point in the ``veles_tpu.plugins`` group.
Importing the module is enough — Unit subclasses self-register via the
UnitRegistry metaclass, loaders/normalizers via their MAPPING registries."""

import importlib
import os
import sys

_loaded = None


def discover(extra_paths=()):
    """Import every plugin found; returns {name: module}.  Idempotent."""
    global _loaded
    if _loaded is not None and not extra_paths:
        return _loaded
    found = {}
    # (a) marker files on sys.path package dirs
    for base in list(sys.path) + list(extra_paths):
        if not base or not os.path.isdir(base):
            continue
        try:
            entries = os.listdir(base)
        except OSError:
            continue
        for name in entries:
            pkg_dir = os.path.join(base, name)
            if os.path.isfile(os.path.join(pkg_dir, ".veles_tpu")):
                found[name] = None
    # (b) entry points
    try:
        from importlib import metadata
        for ep in metadata.entry_points(group="veles_tpu.plugins"):
            found[ep.name] = ep.value
    except Exception:   # noqa: BLE001 — no metadata support
        pass
    modules = {}
    for name, target in sorted(found.items()):
        try:
            modules[name] = importlib.import_module(target or name)
        except ImportError as e:
            import logging
            logging.getLogger("plugins").warning(
                "plugin %s failed to import: %s", name, e)
    if not extra_paths:
        _loaded = modules
    return modules
