"""Interactive shell unit (ref veles/interaction.py:49) — drops into an
IPython / code.interact REPL mid-workflow with the workflow's units in
scope, so a running experiment can be inspected and mutated in place —
plus :class:`Manhole`, the reference's bundled debug shell over a unix
socket (veles/external/manhole): attach a REPL to a LIVE training
process from another terminal without stopping it."""

import code
import io
import os
import socket
import threading

from veles_tpu.logger import Logger
from veles_tpu.units import Unit


class Shell(Unit):
    """Opens a REPL each time it runs (gate it like any unit to control
    when).  ``console=`` injects a replacement console callable
    ``fn(locals_dict)`` — used by tests and by non-tty runs."""

    def __init__(self, workflow, console=None, banner=None, **kwargs):
        super(Shell, self).__init__(workflow, **kwargs)
        self.console = console
        self.banner = banner or (
            "veles_tpu shell — `wf` is the workflow, `units` its units; "
            "Ctrl-D resumes the run")

    def _locals(self):
        env = {"wf": self.workflow, "shell": self}
        if self.workflow is not None:
            env["units"] = list(getattr(self.workflow, "units", []))
            for u in env["units"]:
                env.setdefault(u.name.replace(" ", "_"), u)
        return env

    def run(self):
        env = self._locals()
        if self.console is not None:
            self.console(env)
            return
        try:
            from IPython import embed
            embed(user_ns=env, banner1=self.banner)
        except ImportError:
            code.interact(banner=self.banner, local=env)


class _ThreadRouter(io.TextIOBase):
    """stdout/stderr proxy that diverts writes from threads that called
    ``route()`` while leaving every other thread's output untouched."""

    def __init__(self, orig):
        self._orig = orig
        self._local = threading.local()

    def route(self, target):
        self._local.target = target

    def unroute(self):
        self._local.target = None

    def _t(self):
        return getattr(self._local, "target", None) or self._orig

    def write(self, s):
        return self._t().write(s)

    def flush(self):
        return self._t().flush()

    def __getattr__(self, name):
        return getattr(self._orig, name)


_router_lock = threading.Lock()


def _install_thread_router():
    """Idempotently wrap sys.stdout (and stderr) in a _ThreadRouter,
    returning the stdout router (stderr routes to the same session
    target through its own proxy)."""
    import sys
    with _router_lock:
        if not isinstance(sys.stdout, _ThreadRouter):
            sys.stdout = _ThreadRouter(sys.stdout)
        if not isinstance(sys.stderr, _ThreadRouter):
            sys.stderr = _ThreadRouter(sys.stderr)
        stdout_router, stderr_router = sys.stdout, sys.stderr

    class _Pair:
        def route(self, target):
            stdout_router.route(target)
            stderr_router.route(target)

        def unroute(self):
            stdout_router.unroute()
            stderr_router.unroute()

    return _Pair()


class Manhole(Logger):
    """Debug REPL over a unix socket (ref veles/external/manhole —
    activated on demand, never blocks the training loop).

        manhole = Manhole("/tmp/veles.sock", scope={"wf": wf}).start()
        # elsewhere:  socat - UNIX-CONNECT:/tmp/veles.sock

    The socket is chmod 0600 (owner only — it executes code).  Each
    connection gets its own interpreter over a shared ``scope``."""

    def __init__(self, path, scope=None, **kwargs):
        super(Manhole, self).__init__(**kwargs)
        self.path = path
        self.scope = dict(scope or {})
        self._sock = None
        self._thread = None
        self._stop = False

    def start(self):
        if os.path.exists(self.path):
            os.remove(self.path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        # 0600 must hold from the instant the file exists — a permissive
        # umask would otherwise open a connect window before chmod
        saved_umask = os.umask(0o177)
        try:
            self._sock.bind(self.path)
        finally:
            os.umask(saved_umask)
        os.chmod(self.path, 0o600)
        self._sock.listen(1)
        self._sock.settimeout(0.5)

        def accept_loop():
            while not self._stop:
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True).start()

        self._thread = threading.Thread(target=accept_loop, daemon=True)
        self._thread.start()
        self.info("manhole listening on %s", self.path)
        return self

    def _serve(self, conn):
        f = conn.makefile("rw", encoding="utf-8", newline="\n")
        interp = code.InteractiveInterpreter(dict(self.scope))
        out = io.StringIO()

        def write(s):
            out.write(s)
        interp.write = write
        # capture prints from THIS session thread only — a process-wide
        # redirect would hijack the training loop's own stdout (epoch
        # logs, the CLI's contractual JSON lines) mid-evaluation
        stdout_proxy = _install_thread_router()
        try:
            f.write("veles_tpu manhole — scope: %s\n>>> "
                    % sorted(self.scope))
            f.flush()
            buf = []
            for line in f:
                buf.append(line.rstrip("\n"))
                stdout_proxy.route(out)
                try:
                    more = interp.runsource("\n".join(buf))
                finally:
                    stdout_proxy.unroute()
                if not more:
                    buf = []
                f.write(out.getvalue())
                out.truncate(0)
                out.seek(0)
                f.write("... " if more else ">>> ")
                f.flush()
        except (BrokenPipeError, ConnectionResetError, ValueError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        if self._sock is not None:
            self._sock.close()
        if self._thread is not None:
            self._thread.join(timeout=2)
        if os.path.exists(self.path):
            os.remove(self.path)
