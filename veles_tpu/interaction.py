"""Interactive shell unit (ref veles/interaction.py:49) — drops into an
IPython / code.interact REPL mid-workflow with the workflow's units in
scope, so a running experiment can be inspected and mutated in place."""

import code

from veles_tpu.units import Unit


class Shell(Unit):
    """Opens a REPL each time it runs (gate it like any unit to control
    when).  ``console=`` injects a replacement console callable
    ``fn(locals_dict)`` — used by tests and by non-tty runs."""

    def __init__(self, workflow, console=None, banner=None, **kwargs):
        super(Shell, self).__init__(workflow, **kwargs)
        self.console = console
        self.banner = banner or (
            "veles_tpu shell — `wf` is the workflow, `units` its units; "
            "Ctrl-D resumes the run")

    def _locals(self):
        env = {"wf": self.workflow, "shell": self}
        if self.workflow is not None:
            env["units"] = list(getattr(self.workflow, "units", []))
            for u in env["units"]:
                env.setdefault(u.name.replace(" ", "_"), u)
        return env

    def run(self):
        env = self._locals()
        if self.console is not None:
            self.console(env)
            return
        try:
            from IPython import embed
            embed(user_ns=env, banner1=self.banner)
        except ImportError:
            code.interact(banner=self.banner, local=env)
