"""Device benchmark + memory watcher.

``DeviceBenchmark`` reproduces the reference's square-matmul methodology
(ref veles/accelerated_units.py:706-824, backends.py:672-731): time a
size²·size² matmul, repeats best-of-N, and expose ``computing_power`` =
1000/dt — the number the reference's master used for slave load balancing.

``Watcher`` is the TPU stand-in for the reference's device-memory
accounting (ref veles/memory.py:56-107): live-array byte census per device
plus the runtime's own memory_stats when the backend provides them."""

import time

import numpy as np

from veles_tpu.units import Unit


class DeviceBenchmark(Unit):
    def __init__(self, workflow, size=1500, repeats=3, dtype=None, **kwargs):
        super(DeviceBenchmark, self).__init__(workflow, **kwargs)
        self.size = size
        self.repeats = repeats
        self.dtype = dtype
        self.seconds = None
        self.computing_power = None
        self.gflops = None

    def run(self):
        import jax
        import jax.numpy as jnp

        n = self.size
        dtype = self.dtype or jnp.float32
        a = jnp.asarray(np.random.RandomState(0).rand(n, n), dtype)
        f = jax.jit(lambda x: jnp.dot(x, x, precision="highest"))
        jax.block_until_ready(f(a))   # compile + warm
        best = float("inf")
        for _ in range(self.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(f(a))
            best = min(best, time.perf_counter() - t0)
        self.seconds = best
        self.computing_power = 1000.0 / best
        self.gflops = 2.0 * n ** 3 / best / 1e9
        self.info("gemm %dx%d: %.4fs  %.1f GFLOP/s  power %.1f",
                  n, n, best, self.gflops, self.computing_power)


class Watcher(object):
    """Device-memory census: ``snapshot()`` -> {device: bytes of live jax
    arrays}; ``peak`` tracks the high-water mark across snapshots."""

    def __init__(self):
        self.peak = 0

    @staticmethod
    def live_bytes():
        import jax
        per_device = {}
        for arr in jax.live_arrays():
            try:
                # per-shard bytes: replicated arrays cost full size on
                # EVERY device, sharded ones their slice — shard.data has
                # the honest number either way
                for shard in arr.addressable_shards:
                    d = str(shard.device)
                    per_device[d] = (per_device.get(d, 0)
                                     + shard.data.nbytes)
            except RuntimeError:   # deleted under us
                continue
        return per_device

    @staticmethod
    def runtime_stats():
        """Backend-reported stats (bytes_in_use / peak_bytes_in_use on TPU;
        absent on CPU) — the honest HBM number when available."""
        import jax
        stats = {}
        for d in jax.devices():
            try:
                stats[str(d)] = d.memory_stats()
            except Exception:   # noqa: BLE001 — backend without stats
                stats[str(d)] = None
        return stats

    def snapshot(self):
        per_device = self.live_bytes()
        total = sum(per_device.values())
        self.peak = max(self.peak, total)
        return per_device
