"""Device benchmark + memory watcher.

``DeviceBenchmark`` reproduces the reference's square-matmul methodology
(ref veles/accelerated_units.py:706-824, backends.py:672-731): time a
size²·size² matmul, repeats best-of-N, and expose ``computing_power`` =
1000/dt — the number the reference's master used for slave load balancing.

``Watcher`` is the TPU stand-in for the reference's device-memory
accounting (ref veles/memory.py:56-107): live-array byte census per device
plus the runtime's own memory_stats when the backend provides them."""

import time

import numpy as np

from veles_tpu.units import Unit


class DeviceBenchmark(Unit):
    def __init__(self, workflow, size=1500, repeats=3, dtype=None, **kwargs):
        super(DeviceBenchmark, self).__init__(workflow, **kwargs)
        self.size = size
        self.repeats = repeats
        self.dtype = dtype
        self.seconds = None
        self.computing_power = None
        self.gflops = None

    def run(self):
        import jax
        import jax.numpy as jnp

        n = self.size
        dtype = self.dtype or jnp.float32
        a = jnp.asarray(np.random.RandomState(0).rand(n, n), dtype)
        f = jax.jit(lambda x: jnp.dot(x, x, precision="highest"))
        jax.block_until_ready(f(a))   # compile + warm
        best = float("inf")
        for _ in range(self.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(f(a))
            best = min(best, time.perf_counter() - t0)
        self.seconds = best
        self.computing_power = 1000.0 / best
        self.gflops = 2.0 * n ** 3 / best / 1e9
        self.info("gemm %dx%d: %.4fs  %.1f GFLOP/s  power %.1f",
                  n, n, best, self.gflops, self.computing_power)


class Watcher(object):
    """Device-memory census: ``snapshot()`` -> {device: bytes of live jax
    arrays}; ``peak`` tracks the high-water mark across snapshots."""

    def __init__(self):
        self.peak = 0

    @staticmethod
    def live_bytes():
        import jax
        per_device = {}
        for arr in jax.live_arrays():
            try:
                # per-shard bytes: replicated arrays cost full size on
                # EVERY device, sharded ones their slice — shard.data has
                # the honest number either way
                for shard in arr.addressable_shards:
                    d = str(shard.device)
                    per_device[d] = (per_device.get(d, 0)
                                     + shard.data.nbytes)
            except RuntimeError:   # deleted under us
                continue
        return per_device

    @staticmethod
    def runtime_stats():
        """Backend-reported stats (bytes_in_use / peak_bytes_in_use on TPU;
        absent on CPU) — the honest HBM number when available.  CPU
        backends return None (or a dict missing those keys) from
        ``memory_stats()``; callers must treat every key as optional."""
        import jax
        stats = {}
        for d in jax.devices():
            try:
                stats[str(d)] = d.memory_stats()
            except Exception:   # noqa: BLE001 — backend without stats
                stats[str(d)] = None
        return stats

    def snapshot(self):
        per_device = self.live_bytes()
        total = sum(per_device.values())
        self.peak = max(self.peak, total)
        return per_device

    def record(self, registry=None):
        """Set the device-memory gauges from a fresh census: live-array
        bytes per device, the census high-water mark, and — only where
        the backend actually reports them — the runtime's own
        bytes_in_use / peak_bytes_in_use.  ``memory_stats()`` returning
        None or a partial dict (CPU backends) degrades to the census
        gauges instead of raising or printing."""
        if registry is None:
            from veles_tpu.telemetry import registry
        per_device = self.snapshot()
        g_live = registry.gauge("veles_device_live_bytes",
                                "live jax-array bytes per device "
                                "(per-shard census)", ("device",))
        for dev, nbytes in per_device.items():
            g_live.set(nbytes, device=dev)
        registry.gauge("veles_device_peak_bytes",
                       "census high-water mark across snapshots, "
                       "all devices").set(self.peak)
        g_used = registry.gauge("veles_hbm_bytes_in_use",
                                "backend-reported bytes in use "
                                "(absent on CPU)", ("device",))
        g_peak = registry.gauge("veles_hbm_peak_bytes_in_use",
                                "backend-reported peak bytes in use "
                                "(absent on CPU)", ("device",))
        for dev, st in self.runtime_stats().items():
            if not isinstance(st, dict):
                continue            # CPU backend: memory_stats() is None
            used = st.get("bytes_in_use")
            if isinstance(used, (int, float)):
                g_used.set(used, device=dev)
            peak = st.get("peak_bytes_in_use")
            if isinstance(peak, (int, float)):
                g_peak.set(peak, device=dev)
        return per_device
