"""Evaluators (ref Znicz EvaluatorSoftmax / EvaluatorMSE, SURVEY.md §2.9).

The reference's evaluators produce ``err_output`` consumed by hand-written
GD backward units; here the loss scalar feeds ``jax.grad`` and the metric
outputs (n_errors, confusion matrix, max-err) feed the Decision unit."""

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits, labels, n_classes=None):
    """Mean NLL over the batch + error count + confusion matrix.

    :param labels: int class ids [N]
    :returns: dict(loss, n_errors, confusion, predictions)
    """
    n_classes = n_classes or logits.shape[-1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    pred = jnp.argmax(logits, axis=-1)
    errors = (pred != labels).astype(jnp.int32)
    confusion = jnp.zeros((n_classes, n_classes), jnp.int32).at[
        labels, pred].add(1)
    return {"loss": nll.mean(), "n_errors": errors.sum(),
            "confusion": confusion, "predictions": pred}


def mse(output, target):
    """Mean squared error + per-batch RMSE metrics (EvaluatorMSE)."""
    diff = (output - target).astype(jnp.float32).reshape(output.shape[0], -1)
    se = jnp.sum(diff * diff, axis=1)
    return {"loss": se.mean(),
            "rmse": jnp.sqrt(jnp.mean(diff * diff)),
            "max_err": jnp.max(jnp.abs(diff))}


def masked_softmax_xent(logits, labels, valid):
    """Masked-batch softmax cross-entropy sums, for fixed-shape minibatches
    with padded tails (``valid`` is the loader's 0/1 mask).

    :returns: (nll_sum, err_sum, n_valid) — all float32 scalars.
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    pred = jnp.argmax(logits, axis=-1)
    err_sum = jnp.sum((pred != labels).astype(jnp.float32) * valid)
    return jnp.sum(nll * valid), err_sum, jnp.sum(valid)


def masked_seq_xent(logits, labels, valid):
    """Per-timestep softmax cross-entropy for language modeling:
    logits [B, T, V], labels [B, T] int, valid [B] 0/1 sample mask.

    :returns: (nll_sum, err_sum, n_valid_tokens) — float32 scalars.
    """
    b, t, v = logits.shape
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    pred = jnp.argmax(logits, axis=-1)
    tok_valid = jnp.broadcast_to(valid[:, None], (b, t)).astype(jnp.float32)
    err_sum = jnp.sum((pred != labels).astype(jnp.float32) * tok_valid)
    return (jnp.sum(nll * tok_valid), err_sum, jnp.sum(tok_valid))


def masked_mse(output, target, valid):
    """Masked-batch summed squared error.

    :returns: (se_sum, n_valid, n_features).
    """
    diff = (output - target).astype(jnp.float32).reshape(output.shape[0], -1)
    se = jnp.sum(diff * diff, axis=1)
    return jnp.sum(se * valid), jnp.sum(valid), diff.shape[1]


# ---------------------------------------------------------------------------
# Pluggable evaluator registry (the extension seam the reference had via
# its evaluator unit registry — new losses register by name and the
# trainer/StandardWorkflow pick them up without modification).
#
# An entry is ``(fn, kind)``:
#   fn(out, labels, targets, valid) ->
#       (loss_sum, err_sum, n_valid, n_features)  — float32 scalars/ints
#   kind: "class" (Decision watches the error count) or
#         "regression" (Decision watches the loss)
# ---------------------------------------------------------------------------

_LOSSES = {}


def register_loss(name, kind="class", numerics_suppress=()):
    """Decorator: ``@register_loss("focal")`` adds an evaluator usable as
    ``StandardWorkflow(loss="focal")``.

    ``numerics_suppress`` is the explicit "checked" escape hatch for the
    VN4xx/VR5xx numerics audit (docs/static_analysis.md): a loss that
    DELIBERATELY trips a rule — say an int8 evaluation metric whose
    narrowing cast is range-checked by construction — names the rule ids
    here, and ``StagedTrainer.lint_numerics_spec`` carries them into the
    audit as suppressions.  Every loss in this file is written to pass
    clean instead (f32 accumulation, ``maximum(n, 1)`` guards), so the
    built-ins suppress nothing."""
    def deco(fn):
        fn.numerics_suppress = tuple(numerics_suppress)
        _LOSSES[name] = (fn, kind)
        return fn
    return deco


def get_loss(name):
    try:
        return _LOSSES[name]
    except KeyError:
        raise KeyError("unknown loss %r — registered: %s"
                       % (name, sorted(_LOSSES))) from None


@register_loss("softmax", kind="class")
def _softmax_loss(out, labels, targets, valid):
    loss_sum, err_sum, n_valid = masked_softmax_xent(out, labels, valid)
    return loss_sum, err_sum, n_valid, 1


@register_loss("lm", kind="class")
def _lm_loss(out, labels, targets, valid):
    # next-token objective: predict token t+1 from logits at t
    loss_sum, err_sum, n_valid = masked_seq_xent(
        out[:, :-1], labels[:, 1:], valid)
    return loss_sum, err_sum, n_valid, 1


@register_loss("mse", kind="regression")
def _mse_loss(out, labels, targets, valid):
    loss_sum, n_valid, n_features = masked_mse(out, targets, valid)
    return loss_sum, jnp.asarray(0.0), n_valid, n_features
