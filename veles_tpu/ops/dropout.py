"""Dropout (ref Znicz DropoutForward/Backward).

Inverted dropout: train-time mask scaled by 1/(1-p) so inference is the
identity.  The key comes from the unit's named PRNG stream, keeping runs
bit-reproducible (ref reproducibility contract, SURVEY.md §4)."""

import jax
import jax.numpy as jnp


def forward(x, key, dropout_ratio=0.5):
    keep = 1.0 - dropout_ratio
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)
