"""Multi-head attention — naive, blockwise (flash) and Pallas paths.

New capability beyond the reference (SURVEY.md §5 "Long-context /
sequence parallelism: Absent" — the reference predates attention); the
TPU build treats long-context as first-class.  Three implementations with
one contract:

- ``attention``          O(T²) memory reference implementation (einsum),
                         ground truth for the tests.
- ``blockwise_attention``online-softmax ``lax.scan`` over key/value
                         blocks: O(T·block) memory, pure XLA, works on any
                         backend, and is what ring attention reuses per
                         shard (parallel.ring).
- ``flash_attention``    Pallas TPU kernel (ops.pallas.flash), VMEM-tiled;
                         falls back to interpret mode off-TPU.

All take [B, H, T, D] and return [B, H, T, D]; softmax math in f32
regardless of input dtype."""

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


class QuantCache(NamedTuple):
    """int8-quantized KV cache: per-(batch, head, position) symmetric
    scales over the head dim.  Quarters the serve-time cache memory vs
    f32 (halves vs bf16) — the storage bound on long-context serving.
    A pytree, so the decode scan / beam gathers treat it like a plain
    array via tree_map."""

    data: jnp.ndarray      # int8  [B, Hkv, T, hd]
    scale: jnp.ndarray     # f32   [B, Hkv, T, 1]


def quantize_kv(x):
    """x [..., T, hd] → (int8 data, f32 scale[..., T, 1]): symmetric
    per-position quantization over the head dim (the shared
    ops.quant.symmetric_int8 scheme)."""
    from veles_tpu.ops.quant import symmetric_int8
    return symmetric_int8(x)


def dequantize_kv(cache):
    """QuantCache → float array (f32) — the prefill in-chunk view, so
    prefilled positions see exactly what later decode steps will read
    back from the quantized cache."""
    return cache.data.astype(jnp.float32) * cache.scale


def _scale(d, scale=None):
    return 1.0 / math.sqrt(d) if scale is None else scale


def attention(q, k, v, causal=False, scale=None, bias=None, window=None):
    """Reference O(T²) attention.  q,k,v: [B, H, T, D].  ``window`` (with
    causal) keeps only the last ``window`` positions per query — the
    sliding-window mask."""
    *_, tq, d = q.shape
    tk = k.shape[-2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s * _scale(d, scale)
    if bias is not None:
        s = s + bias
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError("window must be >= 1")
    if causal:
        rows = jnp.arange(tq)[:, None] + (tk - tq)
        cols = jnp.arange(tk)[None]
        mask = rows >= cols
        if window is not None:
            mask = mask & (rows - cols < window)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def blockwise_attention(q, k, v, causal=False, scale=None, block_k=512,
                        q_offset=0, k_offset=0, carry=None,
                        return_carry=False, window=None):
    """Online-softmax attention scanning over key blocks.

    ``q_offset``/``k_offset`` are the *global* sequence positions of the
    local q/k shards — this is what lets ring attention apply a correct
    causal mask across devices.  ``carry``/``return_carry`` expose the
    (acc, max, sum) online-softmax state so partial attention over
    different kv shards can be chained (the ring step):

        carry = None
        for each kv shard:
            carry = blockwise_attention(..., carry=carry, return_carry=True)
        out = finalize_attention(carry)
    """
    if window is not None:
        # match flash_attention: never silently ignore or degenerate the
        # sliding window for direct callers of the blockwise entry point
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError("window must be >= 1")
    b, h, tq, d = q.shape
    tk = k.shape[-2]
    block_k = min(block_k, tk)
    nk = -(-tk // block_k)
    pad = nk * block_k - tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    sc = _scale(d, scale)
    qpos = q_offset + jnp.arange(tq)

    if carry is None:
        acc = jnp.zeros((b, h, tq, d), jnp.float32)
        m = jnp.full((b, h, tq), NEG_INF, jnp.float32)
        l = jnp.zeros((b, h, tq), jnp.float32)
    else:
        acc, m, l = carry

    kb = k.reshape(b, h, nk, block_k, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nk, block_k, d).transpose(2, 0, 1, 3, 4)

    def step(carry, inputs):
        acc, m, l = carry
        ki, kblk, vblk = inputs
        kpos = k_offset + ki * block_k + jnp.arange(block_k)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk,
                       preferred_element_type=jnp.float32) * sc
        valid = kpos < (k_offset + tk)          # padding mask
        if causal:
            valid = valid[None, :] & (qpos[:, None] >= kpos[None, :])
            if window is not None:
                valid = valid & (qpos[:, None] - kpos[None, :] < window)
            s = jnp.where(valid, s, NEG_INF)
        else:
            s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard: all-masked rows keep m=NEG_INF; exp(NEG_INF-NEG_INF)=1
        # would poison l, so renormalize against a safe max
        m_safe = jnp.maximum(m_new, -0.5 * abs(NEG_INF))
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        corr = jnp.exp(jnp.maximum(m, -0.5 * abs(NEG_INF)) - m_safe)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, corr)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)
        return (acc, m_new, l), None

    (acc, m, l), _ = lax.scan(step, (acc, m, l),
                              (jnp.arange(nk), kb, vb))
    if return_carry:
        return acc, m, l
    return finalize_attention((acc, m, l)).astype(q.dtype)


def finalize_attention(carry):
    """Normalize the online-softmax accumulator: out = acc / l."""
    acc, _, l = carry
    return acc / jnp.maximum(l, 1e-30)[..., None]


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None, backward="fused",
                    window=None, block_q_dq=None, block_k_dq=None,
                    block_q_dkv=None, block_k_dkv=None):
    """Pallas TPU flash attention (ops.pallas.flash); [B, H, T, D].
    ``window`` = sliding-window causal attention (blocks outside the
    band are skipped entirely — O(T·window) compute).  Block sizes
    (forward and the independent dq/dkv backward grids) default from
    ``root.common.engine.flash.*``, then the kernel autotuner's winner
    cache — None forwards so the kernel-side resolution decides."""
    from veles_tpu.ops.pallas import flash
    return flash.flash_attention(q, k, v, causal=causal,
                                 scale=_scale(q.shape[-1], scale),
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret, backward=backward,
                                 window=window, block_q_dq=block_q_dq,
                                 block_k_dq=block_k_dq,
                                 block_q_dkv=block_q_dkv,
                                 block_k_dkv=block_k_dkv)


# ---------------------------------------------------------------------------
# multi-head attention layer math

def mha_init(rng, d_model, n_heads, dtype=jnp.float32, n_kv_heads=None):
    """QKV + output projection params.  ``rng`` is the framework PRNG
    (veles_tpu.prng RandomGenerator) for reproducibility.

    ``n_kv_heads < n_heads`` = grouped-query attention (GQA): k/v project
    to fewer heads, each shared by ``n_heads // n_kv_heads`` query heads —
    smaller k/v projections (params + FLOPs) and a smaller KV state at
    serve time.  (During training the forward broadcasts k/v back to
    n_heads before the attention core, so peak activation memory there
    matches full MHA.)"""
    if n_kv_heads is None:
        n_kv_heads = n_heads
    if n_heads % n_kv_heads:
        raise ValueError("n_heads %d %% n_kv_heads %d != 0"
                         % (n_heads, n_kv_heads))
    d_kv = (d_model // n_heads) * n_kv_heads
    std = 1.0 / math.sqrt(d_model)
    def w(shape):
        return jnp.asarray(rng.normal(0.0, std, shape), dtype)
    return {
        "wq": w((d_model, d_model)), "wk": w((d_model, d_kv)),
        "wv": w((d_model, d_kv)), "wo": w((d_model, d_model)),
        "bq": jnp.zeros((d_model,), dtype), "bk": jnp.zeros((d_kv,), dtype),
        "bv": jnp.zeros((d_kv,), dtype), "bo": jnp.zeros((d_model,), dtype),
    }


def split_heads(x, n_heads):
    b, t, dm = x.shape
    return x.reshape(b, t, n_heads, dm // n_heads).transpose(0, 2, 1, 3)


def merge_heads(x):
    return x.transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[2], -1)


def _proj(x, w, b, policy):
    from veles_tpu.ops.quant import is_quant, quant_matmul
    if is_quant(w):
        # quantized serving weights (int8 W8A8 / w4a8): the payload
        # stays narrow into the dot, cutting decode HBM traffic
        return quant_matmul(x, w) + b.astype(jnp.float32)
    if policy is None:
        return x @ w + b
    y = jnp.matmul(policy.cast_in(x), policy.cast_in(w),
                   preferred_element_type=policy.accum)
    return y + b.astype(policy.accum)


def mha_forward(params, x, n_heads, causal=False, impl="blockwise",
                attn_fn=None, policy=None, n_kv_heads=None,
                use_rope=False, window=None):
    """x: [B, T, d_model] → [B, T, d_model].

    ``attn_fn(q, k, v, causal)`` overrides the core attention — this is the
    hook ring/Ulysses sequence parallelism plugs into (parallel.ring).
    ``policy`` (ops.policy.Policy) casts the projection matmuls and the
    attention inputs to the compute dtype (bf16 on the MXU).
    ``n_kv_heads`` enables GQA: k/v heads broadcast to the query heads
    before the core attention (same kernels, smaller projections).
    ``use_rope`` rotates q/k by absolute position (rope()).
    ``window`` = sliding-window causal attention (all impls share the
    q - k < window mask)."""
    if window is not None:
        # every backend also validates this itself; kept here so the
        # error precedes the projection matmuls
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError("window must be >= 1")
    if n_kv_heads is None:
        n_kv_heads = n_heads
    q, k, v = _qkv_proj(params, x, n_heads, n_kv_heads, policy)
    if use_rope:
        # rotation happens on the GLOBAL [B, H, T, D] arrays, before any
        # sequence-parallel shard_map (ring/Ulysses take global arrays
        # and shard internally) — positions are the true 0..T-1
        pos = jnp.arange(x.shape[1])
        q = rope(q, pos)
        k = rope(k, pos)
    k, v = _broadcast_kv(k, v, n_heads, n_kv_heads)
    if attn_fn is None:
        if impl == "naive":
            attn_fn = attention
        elif impl == "flash":
            attn_fn = flash_attention
        else:
            attn_fn = blockwise_attention
        if window is not None:
            attn_fn = functools.partial(attn_fn, window=window)
    elif window is not None:
        raise ValueError("window is not supported with sequence-"
                         "parallel attention (impl=ring/ulysses)")
    o = attn_fn(q, k, v, causal=causal)
    return _proj(merge_heads(o), params["wo"], params["bo"], policy)


def _qkv_proj(params, x, n_heads, n_kv_heads, policy):
    """Shared q/k/v projection + head split (mha_forward, mha_prefill,
    mha_step all route through here so they can never drift apart).

    LoRA (Hu et al. 2021, the standard q/v recipe): an optional
    ``params["lora"]`` sub-dict carries rank-r factors qa/qb and va/vb;
    the effective projections become Wq + qa·qb and Wv + va·vb.  Every
    decode path inherits the adapters through this one chokepoint.
    (Base-weight freezing is the LAYER's job — TransformerBlock
    stop_gradients everything but the lora subtree at train time.)"""
    cast = (lambda t: t) if policy is None else policy.cast_in
    lora = params.get("lora")

    def proj(wk_, bk_, ak_, bk2_, heads):
        y = _proj(x, params[wk_], params[bk_], policy)
        if lora is not None and ak_ in lora:
            d = jnp.matmul(jnp.matmul(cast(x), cast(lora[ak_])),
                           cast(lora[bk2_]))
            y = y + d.astype(y.dtype)
        return split_heads(cast(y), heads)

    q = proj("wq", "bq", "qa", "qb", n_heads)
    # k carries NO adapters (the standard q/v-only recipe) — plain base
    k = split_heads(cast(_proj(x, params["wk"], params["bk"], policy)),
                    n_kv_heads)
    v = proj("wv", "bv", "va", "vb", n_kv_heads)
    return q, k, v


def _broadcast_kv(k, v, n_heads, n_kv_heads):
    """GQA: broadcast kv heads up to the query heads."""
    if n_kv_heads != n_heads:
        rep = n_heads // n_kv_heads
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    return k, v


def mha_prefill(params, x, cache_k, cache_v, n_heads, n_kv_heads=None,
                scale=None, policy=None, use_rope=False, window=None):
    """Chunked prefill: run the WHOLE prompt chunk x [B, Tp, d_model]
    through attention in one parallel pass (blockwise core — O(Tp·block)
    memory) and write its k/v into cache positions [0, Tp).

    Equivalent to Tp sequential mha_step calls, at full-forward cost:
    position i attends [0, i] via the causal(+window) mask, the cache
    stores k/v with mha_step's EXACT dtype ordering (cast to the cache
    dtype BEFORE the rope rotation), and the in-chunk attention reads
    the cache-dtype k/v — the same view mha_step sees.
    Returns (y [B, Tp, d_model], cache_k, cache_v)."""
    if n_kv_heads is None:
        n_kv_heads = n_heads
    quant = isinstance(cache_k, QuantCache)
    t_cache = (cache_k.data if quant else cache_k).shape[2]
    tp = x.shape[1]
    rolling = window is not None and t_cache == window
    q, k, v = _qkv_proj(params, x, n_heads, n_kv_heads, policy)
    if not quant:
        k = k.astype(cache_k.dtype)
        v = v.astype(cache_v.dtype)
    if use_rope:
        pos = jnp.arange(tp)
        q = rope(q, pos)
        k = (rope(k, pos) if quant
             else rope(k, pos).astype(cache_k.dtype))

    if rolling:
        # ring buffer: keep only the chunk's LAST min(tp, window)
        # positions; each lands in its slot (pos % window) — unique
        # slots, so the scatter has no duplicate-index hazard.  The
        # caller guarantees every chunk position is a real prompt token
        # (models.generate rounds the prefill chunk DOWN), so after
        # this write slot i holds the latest position <= tp - 1.
        keep = min(tp, window)
        tail_pos = jnp.arange(tp - keep, tp)
        slots = tail_pos % window

        def cache_write(cache, val):
            if not quant:
                return (cache.at[:, :, slots, :]
                        .set(val[:, :, tp - keep:, :]), val)
            # quantize the WHOLE chunk for the in-chunk view (in-chunk
            # queries attend head positions too, and the sequential
            # path reads everything quantized — the views must match);
            # only the tail slots are stored
            d, s = quantize_kv(val)
            new = QuantCache(
                cache.data.at[:, :, slots, :]
                .set(d[:, :, tp - keep:, :]),
                cache.scale.at[:, :, slots, :]
                .set(s[:, :, tp - keep:, :]))
            return new, dequantize_kv(QuantCache(d, s)).astype(val.dtype)
    else:
        def cache_write(cache, val):
            if not quant:
                return jax.lax.dynamic_update_slice(
                    cache, val, (0, 0, 0, 0)), val
            d, s = quantize_kv(val)
            new = QuantCache(
                jax.lax.dynamic_update_slice(cache.data, d,
                                             (0, 0, 0, 0)),
                jax.lax.dynamic_update_slice(cache.scale, s,
                                             (0, 0, 0, 0)))
            # the in-chunk attention must see the QUANTIZED view —
            # exactly what later decode steps read back from the cache
            return new, dequantize_kv(QuantCache(d, s)).astype(val.dtype)

    write = cache_write

    cache_k, k = write(cache_k, k)
    cache_v, v = write(cache_v, v)
    k, v = _broadcast_kv(k, v, n_heads, n_kv_heads)
    o = blockwise_attention(q, k, v, causal=True, scale=scale,
                            window=window)
    return (_proj(merge_heads(o), params["wo"], params["bo"], policy),
            cache_k, cache_v)


def mha_chunk_step(params, x, cache_k, cache_v, start, n_heads,
                   n_kv_heads=None, scale=None, policy=None,
                   use_rope=False, window=None):
    """K incremental positions in ONE parallel pass against an existing
    cache: x [B, K, d_model] holds the tokens at positions
    [start, start + K); their k/v write into the cache and every row i
    attends cache positions <= start + i (+ sliding window) — the
    speculative-decoding verify step.  Linear caches only (a rolling
    ring's slot->position map cannot tolerate the rejected-draft tail
    this writes past the cursor).  ``start`` is traced.
    Returns (y [B, K, d_model], cache_k, cache_v)."""
    if n_kv_heads is None:
        n_kv_heads = n_heads
    quant = isinstance(cache_k, QuantCache)
    kk = x.shape[1]
    q, k1, v1 = _qkv_proj(params, x, n_heads, n_kv_heads, policy)
    if not quant:
        k1 = k1.astype(cache_k.dtype)
        v1 = v1.astype(cache_v.dtype)
    if use_rope:
        pos = start + jnp.arange(kk)
        q = rope(q, pos)
        k1 = (rope(k1, pos) if quant
              else rope(k1, pos).astype(cache_k.dtype))

    def write(cache, val):
        if not quant:
            return jax.lax.dynamic_update_slice(cache, val,
                                                (0, 0, start, 0))
        d, s = quantize_kv(val)
        return QuantCache(
            jax.lax.dynamic_update_slice(cache.data, d,
                                         (0, 0, start, 0)),
            jax.lax.dynamic_update_slice(cache.scale, s,
                                         (0, 0, start, 0)))

    cache_k = write(cache_k, k1)
    cache_v = write(cache_v, v1)

    b, h, _, hd = q.shape
    g = h // n_kv_heads
    qg = q.reshape(b, n_kv_heads, g * kk, hd)   # flatten (group, K)
    if quant:
        s = jnp.einsum("bkgd,bktd->bkgt", qg,
                       cache_k.data.astype(qg.dtype),
                       preferred_element_type=jnp.float32)
        s = s * cache_k.scale[..., 0][:, :, None, :]
    else:
        s = jnp.einsum("bkgd,bktd->bkgt", qg, cache_k,
                       preferred_element_type=jnp.float32)
    s = s.reshape(b, n_kv_heads, g, kk, -1)
    s = s * _scale(hd, scale)
    t_cache = (cache_k.data if quant else cache_k).shape[2]
    positions = jnp.arange(t_cache)[None, None, None, None, :]
    rows = start + jnp.arange(kk)[None, None, None, :, None]
    live = positions <= rows
    if window is not None:
        live = live & (rows - positions < window)
    s = jnp.where(live, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).reshape(b, n_kv_heads, g * kk, -1)
    if quant:
        pv = p * cache_v.scale[..., 0][:, :, None, :]
        o = jnp.einsum("bkgt,bktd->bkgd", pv.astype(qg.dtype),
                       cache_v.data.astype(qg.dtype),
                       preferred_element_type=jnp.float32)
    else:
        o = jnp.einsum("bkgt,bktd->bkgd", p.astype(cache_v.dtype),
                       cache_v, preferred_element_type=jnp.float32)
    # [b, kv, g*kk, hd] -> [b, kk, kv, g, hd] -> [b, kk, h*hd]
    # (head index = kv*g + gi, matching split_heads/merge_heads)
    o = jnp.transpose(o.reshape(b, n_kv_heads, g, kk, hd),
                      (0, 3, 1, 2, 4))
    o = o.reshape(b, kk, h * hd).astype(x.dtype)
    return (_proj(o, params["wo"], params["bo"], policy),
            cache_k, cache_v)


def mha_step(params, x, cache_k, cache_v, pos, n_heads, n_kv_heads=None,
             scale=None, policy=None, use_rope=False, window=None):
    """One incremental-decoding step with a KV cache.

    x: [B, 1, d_model] (the token at position ``pos``);
    cache_k/cache_v: [B, n_kv_heads, T_cache, head_dim] — the cache
    stores KV HEADS ONLY, so GQA's smaller KV state is realized here
    (the query groups attend to the shared kv head without
    materializing copies) — or QuantCache pairs (int8 data +
    per-position scales; the scores fold the scales in after the
    int8-input einsum, so no dequantized [B, H, T, hd] copy ever
    materializes).

    ROLLING cache: with a sliding ``window``, T_cache == window means
    the cache is a ring buffer — position ``pos`` lives in slot
    ``pos % window`` and slot ``i`` holds absolute position
    ``pos - ((pos - i) % window)`` (the latest position <= pos mapping
    to that slot).  Serve-time memory is then O(window) regardless of
    context length.  T_cache > window keeps the linear layout.
    Returns (y [B, 1, d_model], cache_k, cache_v) with position ``pos``
    written."""
    if n_kv_heads is None:
        n_kv_heads = n_heads
    quant = isinstance(cache_k, QuantCache)
    kdt = cache_k.data.dtype if quant else cache_k.dtype
    t_cache = (cache_k.data if quant else cache_k).shape[2]
    rolling = window is not None and t_cache == window
    slot = (pos % window) if rolling else pos
    q, k1, v1 = _qkv_proj(params, x, n_heads, n_kv_heads, policy)
    if not quant:
        k1 = k1.astype(cache_k.dtype)                  # [B, Hkv, 1, hd]
        v1 = v1.astype(cache_v.dtype)
    if use_rope:
        p1 = jnp.full((1,), pos, jnp.int32)
        q = rope(q, p1)
        k1 = (rope(k1, p1) if quant
              else rope(k1, p1).astype(kdt))   # cache stores rotated k

    def write(cache, val):
        if not quant:
            return jax.lax.dynamic_update_slice(cache, val,
                                                (0, 0, slot, 0))
        d, s = quantize_kv(val)
        return QuantCache(
            jax.lax.dynamic_update_slice(cache.data, d,
                                         (0, 0, slot, 0)),
            jax.lax.dynamic_update_slice(cache.scale, s,
                                         (0, 0, slot, 0)))

    cache_k = write(cache_k, k1)
    cache_v = write(cache_v, v1)

    b, h, _, hd = q.shape
    g = h // n_kv_heads
    qg = q.reshape(b, n_kv_heads, g, hd)
    if quant:
        s = jnp.einsum("bkgd,bktd->bkgt", qg,
                       cache_k.data.astype(qg.dtype),
                       preferred_element_type=jnp.float32)
        # fold the per-position k scales in AFTER the dot
        s = s * cache_k.scale[..., 0][:, :, None, :]
    else:
        s = jnp.einsum("bkgd,bktd->bkgt", qg, cache_k,
                       preferred_element_type=jnp.float32)
    s = s * _scale(hd, scale)
    if rolling:
        # slot i holds absolute position pos - ((pos - i) % window):
        # always inside the window by construction, live once written
        slots = jnp.arange(window)[None, None, None, :]
        p_slot = pos - ((pos - slots) % window)
        live = p_slot >= 0
    else:
        positions = jnp.arange(t_cache)[None, None, None, :]
        live = positions <= pos
        if window is not None:
            live = live & (pos - positions < window)
    s = jnp.where(live, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if quant:
        # fold the per-position v scales into the probabilities
        pv = p * cache_v.scale[..., 0][:, :, None, :]
        o = jnp.einsum("bkgt,bktd->bkgd", pv.astype(qg.dtype),
                       cache_v.data.astype(qg.dtype),
                       preferred_element_type=jnp.float32)
    else:
        o = jnp.einsum("bkgt,bktd->bkgd", p.astype(cache_v.dtype),
                       cache_v, preferred_element_type=jnp.float32)
    o = o.reshape(b, 1, h * hd).astype(x.dtype)
    return (_proj(o, params["wo"], params["bo"], policy),
            cache_k, cache_v)


def _rope_rows(x, pos):
    """rope() at PER-ROW positions: x [B, H, 1, hd], pos [B] int32 —
    the continuous batcher decodes every slot at its own depth, so the
    rotation angle differs per batch row (rope() itself broadcasts one
    [T] position vector over the batch)."""
    return jax.vmap(lambda xb, pb: rope(xb[None], pb[None])[0])(
        x, pos.astype(jnp.int32))


def mha_step_paged(params, x, pool_k, pool_v, table, pos, n_heads,
                   n_kv_heads=None, scale=None, policy=None,
                   use_rope=False):
    """One incremental-decoding step against a PAGED KV pool.

    The paged continuous batcher's fused path: instead of gathering
    each row's pool blocks into a dense [B, Hkv, T, hd] cache view and
    calling mha_step, the new k/v scatter straight into their pool
    block and the attention reads the pool through the block table
    (ops.pallas.paged — scalar-prefetch kernel, no dense
    re-materialization).

    x: [B, 1, d_model] — every row decodes its OWN position ``pos[b]``
    (a [B] vector, unlike mha_step's scalar: slots run at different
    depths).  pool_k/pool_v: [1+P, Hkv, block, hd], block 0 reserved —
    or QuantCache pairs (int8 data + f32 per-position scales): the new
    k/v quantize at the write exactly like mha_step's dense int8
    cache, and the kernel streams the int8 pool from HBM and
    dequantizes in VMEM with f32 accumulation.  table: [B, nbm] int32
    pool-block ids; row b's key at absolute position t lives in pool
    block table[b, t // block], offset t % block.

    Sliding windows are not supported here — the batcher's gather path
    remains the fallback (and rolling windows are already rejected at
    pool construction).
    Returns (y [B, 1, d_model], pool_k, pool_v) with ``pos`` written.
    """
    from veles_tpu.ops.pallas.paged import paged_attention_decode
    if n_kv_heads is None:
        n_kv_heads = n_heads
    quant = isinstance(pool_k, QuantCache)
    pos = pos.astype(jnp.int32)
    q, k1, v1 = _qkv_proj(params, x, n_heads, n_kv_heads, policy)
    if not quant:
        k1 = k1.astype(pool_k.dtype)
        v1 = v1.astype(pool_v.dtype)
    if use_rope:
        q = _rope_rows(q, pos)
        k1 = (_rope_rows(k1, pos) if quant
              else _rope_rows(k1, pos).astype(pool_k.dtype))

    bs = (pool_k.data if quant else pool_k).shape[2]
    rows = jnp.arange(x.shape[0])
    blk = table[rows, pos // bs]
    off = pos % bs

    # write targets are exclusively-owned blocks: allocation is a
    # host-side free-list pop, and prefix-SHARED blocks are never
    # write targets (the batcher shares only blocks strictly before
    # any owner's first written position, _shareable_blocks) — so the
    # [B]-indexed scatter has no duplicate hazard
    def write(pool, val):
        if not quant:
            return pool.at[blk, :, off].set(val[:, :, 0])
        d, s = quantize_kv(val)              # [B, Hkv, 1, hd]/[..., 1]
        return QuantCache(pool.data.at[blk, :, off].set(d[:, :, 0]),
                          pool.scale.at[blk, :, off].set(s[:, :, 0]))

    pool_k = write(pool_k, k1)
    pool_v = write(pool_v, v1)

    b, h, _, hd = q.shape
    # the kernel runs the MXU in the pool dtype (bf16 serving; int8
    # pools dequantize in kernel to f32); the dense einsum path mixes
    # f32 q with the cache dtype instead — numerics differ at the
    # last-ulp level, same as flash vs naive
    qk = q[:, :, 0] if quant else q[:, :, 0].astype(pool_k.dtype)
    o = paged_attention_decode(qk, pool_k, pool_v, table, pos,
                               scale=_scale(hd, scale))
    o = o.reshape(b, 1, h * hd).astype(x.dtype)
    return (_proj(o, params["wo"], params["bo"], policy),
            pool_k, pool_v)


def rope(x, positions, base=10000.0):
    """Rotary position embedding (RoFormer).  x: [B, H, T, D] with D
    even; ``positions`` [T] int — rotates consecutive (even, odd) feature
    pairs by position-dependent angles, encoding relative offsets in the
    q·k inner product (no position table, extrapolates past train
    length)."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, None]            # [1, 1, T, half]
    sin = jnp.sin(angles)[None, None]
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    rot = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rot.reshape(x.shape).astype(x.dtype)
