"""Activation functions with the reference's exact formulas
(Znicz activation units, SURVEY.md §2.9 "Activations").

Note the Veles quirks preserved for parity:
  * ``All2AllTanh`` is the scaled LeCun tanh 1.7159·tanh(0.6666·x);
  * Veles ``RELU`` is the *smooth* log(1+exp(x)) (softplus);
    ``StrictRELU`` is max(0, x).
All are elementwise — XLA fuses them into the preceding matmul/conv, so
they cost no extra HBM round-trip."""

import jax
import jax.numpy as jnp


def tanh(x):
    """Scaled LeCun tanh (Veles All2AllTanh / ConvTanh forward)."""
    return 1.7159 * jnp.tanh(0.6666 * x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def relu(x):
    """Veles 'RELU' = softplus (smooth), numerically-stable form."""
    return jax.nn.softplus(x)


def strict_relu(x):
    """Veles 'StrictRELU' = the usual max(0, x)."""
    return jnp.maximum(x, 0.0)


def log(x):
    """Veles ActivationLog: log(x + sqrt(x^2 + 1)) = asinh(x)."""
    return jnp.arcsinh(x)


def tanhlog(x):
    """Veles ActivationTanhLog: scaled tanh below a threshold, log above —
    keeps gradients alive for large |x|."""
    d = 3.0
    a = 0.242528761112
    b = 305.459953195
    tan = 1.7159 * jnp.tanh(x * 0.6666)
    lg = jnp.log(jnp.abs(x) * b + 1.0) * a * jnp.sign(x)
    return jnp.where(jnp.abs(x) <= d, tan, lg)


def sincos(x):
    """Veles ActivationSinCos: even indices -> sin, odd -> cos."""
    flat = x.reshape(x.shape[0], -1)
    idx = jnp.arange(flat.shape[1])
    out = jnp.where(idx % 2 == 0, jnp.sin(flat), jnp.cos(flat))
    return out.reshape(x.shape)


def mul(x, y):
    """Veles ActivationMul: elementwise product of two inputs."""
    return x * y


#: name → fn registry used by StandardWorkflow layer-type suffixes
ACTIVATIONS = {
    "linear": lambda x: x,
    "tanh": tanh,
    "sigmoid": sigmoid,
    "relu": relu,
    "strict_relu": strict_relu,
    "log": log,
    "tanhlog": tanhlog,
    "sincos": sincos,
}
