"""Pure-JAX compute library — the TPU-native replacement for the reference's
kernel library (`ocl/*.cl`, `cuda/*.cu`) and the Znicz unit math
(SURVEY.md §2.4, §2.9).

Every function here is jittable, shape-static, and dtype-policy aware:
matmuls/convs run in the policy's compute dtype (bfloat16 on the MXU) with
float32 accumulation — the TPU equivalent of the reference's Kahan /
multipartial ``PRECISION_LEVEL`` compensated summation
(`ocl/matrix_multiplication_subsum.cl:36-62`)."""

from veles_tpu.ops.policy import Policy, default_policy
from veles_tpu.ops import (activations, conv, dropout, linear, losses, lrn,
                           misc, pooling)

__all__ = [
    "Policy", "default_policy",
    "activations", "conv", "dropout", "linear", "losses", "lrn", "misc",
    "pooling",
]
