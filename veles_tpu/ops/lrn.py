"""Local response normalization across channels (ref Znicz
LRNormalizerForward/Backward, the "norm" layer type — AlexNet-style).

y = x / (k + alpha * sum_{j in [c-n/2, c+n/2]} x_j^2) ** beta

Defaults match the Veles unit (alpha=1e-4, beta=0.75, n=15, k=2)."""

import jax.lax as lax
import jax.numpy as jnp


def forward(x, alpha=1e-4, beta=0.75, n=15, k=2.0):
    sq = x * x
    # sliding-window sum over the channel axis with SAME padding
    window = (1, 1, 1, n)
    ssum = lax.reduce_window(sq, 0.0, lax.add, window, (1, 1, 1, 1), "SAME")
    return x * (k + alpha * ssum) ** (-beta)
