"""Convolution / deconvolution ops (ref Znicz Conv*/Deconv units,
SURVEY.md §2.9 "Conv").

Layout is NHWC with HWIO kernels — the TPU-preferred layout (channels on the
lane dimension feeds the MXU directly).  The reference unrolled conv into its
tiled matmul kernel; XLA's conv emitter owns that on TPU."""

import jax.lax as lax
import jax.numpy as jnp

from veles_tpu.ops.policy import Policy

DIMS = ("NHWC", "HWIO", "NHWC")


def forward(params, x, stride=(1, 1), padding="VALID", policy=Policy()):
    """Conv forward: x [N,H,W,C] * kernel [kh,kw,C,K] + bias [K].

    ``padding`` accepts "VALID"/"SAME" or the reference's explicit
    (pad_top, pad_left, pad_bottom, pad_right) tuple."""
    pad = _padding(padding)
    # no preferred_element_type: its VJP rejects mixed dtypes; the MXU still
    # accumulates bf16 products in f32 internally, we upcast the result
    y = lax.conv_general_dilated(
        policy.cast_in(x), policy.cast_in(params["weights"]),
        window_strides=stride, padding=pad, dimension_numbers=DIMS)
    y = y.astype(policy.accum)
    if "bias" in params:
        y = y + params["bias"].astype(policy.accum)
    return y


def deconv_forward(params, x, stride=(1, 1), padding="VALID",
                   policy=Policy()):
    """Deconv (transposed conv) forward (ref Znicz Deconv; the decoder half
    of conv autoencoders)."""
    pad = _padding(padding)
    y = lax.conv_transpose(
        policy.cast_in(x), policy.cast_in(params["weights"]),
        strides=stride, padding=pad, dimension_numbers=DIMS)
    y = y.astype(policy.accum)
    if "bias" in params:
        y = y + params["bias"].astype(policy.accum)
    return y


def _padding(padding):
    if isinstance(padding, str):
        return padding
    pt, pl, pb, pr = padding
    return ((pt, pb), (pl, pr))


def init_params(rng, kx, ky, n_channels, n_kernels, bias=True,
                weights_stddev=None, dtype=jnp.float32):
    """Filler matching the dense default: uniform [-s, s],
    s = 1/sqrt(fan_in)."""
    fan_in = kx * ky * n_channels
    s = weights_stddev if weights_stddev is not None else fan_in ** -0.5
    params = {"weights":
              rng.fill_uniform((ky, kx, n_channels, n_kernels), s)
              .astype(dtype)}
    if bias:
        params["bias"] = jnp.zeros((n_kernels,), dtype)
    return params
