"""Shared analytic FLOP conventions + the lm_large bench ladder.

Single source of truth for the MFU numerator and the attention FLOP
count, imported by BOTH ``bench.py`` (measurement) and
``tools/cost_model.py`` (prediction) so the two can never silently
diverge — predicted-vs-measured is only meaningful when both sides
count the same FLOPs.  (The reference's device DB had the same
property: one methodology produced both the stored numbers and the
runtime estimates, ref veles/backends.py:672-731.)"""


#: bytes per element by dtype name — the ONE byte-pricing table shared by
#: ``tools/cost_model.py`` (HBM-traffic terms) and the VS2xx/VM3xx
#: sharding/memory auditor (``veles_tpu.analysis.sharding_audit``), keyed
#: by both numpy/jax dtype names and the short HLO/StableHLO tokens that
#: appear in compiled-module text.
DTYPE_BYTES = {
    "pred": 1, "bool": 1,
    "s8": 1, "u8": 1, "int8": 1, "uint8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1, "f8e3m4": 1,
    "s16": 2, "u16": 2, "int16": 2, "uint16": 2,
    "f16": 2, "bf16": 2, "float16": 2, "bfloat16": 2,
    "s32": 4, "u32": 4, "int32": 4, "uint32": 4, "f32": 4, "float32": 4,
    "s64": 8, "u64": 8, "int64": 8, "uint64": 8, "f64": 8, "float64": 8,
    "c64": 8, "complex64": 8, "c128": 16, "complex128": 16,
}


def dtype_nbytes(dtype):
    """Bytes per element of ``dtype`` — accepts a numpy/jax dtype, a dtype
    name, or an HLO shape token ("f32", "bf16", ...)."""
    name = getattr(dtype, "name", None) or str(dtype)
    try:
        return DTYPE_BYTES[name]
    except KeyError:
        import numpy as np
        return int(np.dtype(name).itemsize)


def shape_nbytes(shape, dtype):
    """Bytes of one dense tensor, priced with :data:`DTYPE_BYTES`."""
    n = dtype_nbytes(dtype)
    for d in shape:
        n *= int(d)
    return n


def causal_attn_flops(b, h, t, d):
    """Matmul FLOPs of ONE causal attention call (qk + pv, each 2·b·h·
    t·(t/2)·d with the triangular mask halving effective keys)."""
    return 4 * b * h * t * t * d / 2


def lm_train_flops_per_token(d_model, n_layers, seq, vocab, d_ff=None,
                             n_heads=None, n_kv_heads=None):
    """Analytic matmul FLOPs per trained token (fwd+bwd = 3x fwd): per
    layer q/o project 2·d² each, k/v project 2·d·d_kv each (GQA shrinks
    d_kv = d·n_kv/n_heads), MLP 2·(2·d_ff·d), causal attention 2·T·d
    (T/2 effective keys, qk + pv), plus the 2·d·V LM head.  Embedding
    lookup is a gather — no FLOPs."""
    d_ff = d_ff or 4 * d_model
    kv_frac = ((n_kv_heads / n_heads)
               if n_heads and n_kv_heads else 1.0)
    per_layer = ((4 + 4 * kv_frac) * d_model ** 2
                 + 4 * d_ff * d_model + 2 * seq * d_model)
    return 3 * (n_layers * per_layer + 2 * d_model * vocab)


#: lm_large memory ladder, best rung first: (remat, batch, bench_steps,
#: recompute_frac).  ``recompute_frac`` is the extra forward recomputed
#: in the backward (full remat = 1.0; "dots" keeps matmul outputs so no
#: matmul recompute) — bench walks the rungs on OOM, the cost model
#: predicts each rung's MFU from the same tuple.
LM_LARGE_LADDER = (("dots", 16, 8, 0.0), (True, 16, 8, 1.0),
                   (True, 8, 12, 1.0))
