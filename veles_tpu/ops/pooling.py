"""Pooling ops (ref Znicz Max/Avg/MaxAbs/Stochastic pooling units,
SURVEY.md §2.9 "Pooling").  NHWC layout.

MaxAbsPooling keeps the *signed* value of the max-|x| element (Veles
semantics for tanh-centered activations).  Stochastic pooling draws the
kept element with probability proportional to |activation| (train-time
regularizer); its RNG is a jax key threaded from the unit's named stream,
so runs stay bit-reproducible."""

import jax
import jax.lax as lax
import jax.numpy as jnp


def _window(kx, ky, stride):
    sy, sx = stride if stride is not None else (ky, kx)
    return (1, ky, kx, 1), (1, sy, sx, 1)


def max_pool(x, ky, kx, stride=None, padding="VALID"):
    dims, strides = _window(kx, ky, stride)
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, padding)


def avg_pool(x, ky, kx, stride=None, padding="VALID"):
    dims, strides = _window(kx, ky, stride)
    s = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    ones = jnp.ones_like(x)
    n = lax.reduce_window(ones, 0.0, lax.add, dims, strides, padding)
    return s / n


def max_abs_pool(x, ky, kx, stride=None, padding="VALID"):
    """Signed value of the element with the largest |x| in each window."""
    dims, strides = _window(kx, ky, stride)

    def pick(a, b):
        return lax.select(lax.abs(a) >= lax.abs(b), a, b)

    return lax.reduce_window(x, 0.0, pick, dims, strides, padding)


def _patches(x, ky, kx, stride):
    """[N,Ho,Wo,ky*kx,C] view of pooling windows (general stride)."""
    n, h, w, c = x.shape
    sy, sx = stride if stride is not None else (ky, kx)
    ho = (h - ky) // sy + 1
    wo = (w - kx) // sx + 1
    idx_y = jnp.arange(ho)[:, None] * sy + jnp.arange(ky)[None, :]
    idx_x = jnp.arange(wo)[:, None] * sx + jnp.arange(kx)[None, :]
    # gather rows then cols; shapes [N,Ho,ky,W,C] -> [N,Ho,ky,Wo,kx,C]
    g = x[:, idx_y.reshape(-1), :, :].reshape(n, ho, ky, w, c)
    g = g[:, :, :, idx_x.reshape(-1), :].reshape(n, ho, ky, wo, kx, c)
    return g.transpose(0, 1, 3, 2, 4, 5).reshape(n, ho, wo, ky * kx, c)


def stochastic_pool(x, ky, kx, key, stride=None, absolute=False):
    """Zeiler-style stochastic pooling: sample one element per window with
    p ∝ activation (or |activation| for the Abs variant).  All-zero windows
    yield 0 (matching max-pool of zeros)."""
    p = _patches(x, ky, kx, stride)          # [N,Ho,Wo,K,C]
    mag = jnp.abs(p) if absolute else jnp.maximum(p, 0.0)
    total = mag.sum(axis=3, keepdims=True)
    probs = jnp.where(total > 0, mag / jnp.where(total > 0, total, 1.0), 0.0)
    # categorical over the window axis, per (n, ho, wo, c)
    logits = jnp.where(probs > 0, jnp.log(probs), -jnp.inf)
    logits = jnp.moveaxis(logits, 3, -1)      # [N,Ho,Wo,C,K]
    choice = jax.random.categorical(key, logits, axis=-1)  # [N,Ho,Wo,C]
    gathered = jnp.take_along_axis(
        jnp.moveaxis(p, 3, -1), choice[..., None], axis=-1)[..., 0]
    any_mass = jnp.moveaxis(total, 3, -1)[..., 0] > 0
    return jnp.where(any_mass, gathered, 0.0)


def stochastic_pool_infer(x, ky, kx, stride=None, absolute=False):
    """Inference-time stochastic pooling = probability-weighted average
    (Zeiler §3; what StochasticPooling computes when not training)."""
    p = _patches(x, ky, kx, stride)
    mag = jnp.abs(p) if absolute else jnp.maximum(p, 0.0)
    total = mag.sum(axis=3, keepdims=True)
    w = jnp.where(total > 0, mag / jnp.where(total > 0, total, 1.0), 0.0)
    return (p * w).sum(axis=3)


def stochastic_pool_depool(x, ky, kx, key, absolute=False):
    """Fused StochasticPoolingDepooling (ref Znicz unit of the same name):
    sample one element per non-overlapping window with p ∝ activation
    (|activation| for the Abs flavor), keep it *in place*, zero the rest —
    output has the input's spatial shape (trailing rows/cols that don't fill
    a window pass through zeroed, matching VALID pooling coverage)."""
    n, h, w, c = x.shape
    p = _patches(x, ky, kx, (ky, kx))        # [N,Ho,Wo,K,C], K = ky*kx
    ho, wo = p.shape[1], p.shape[2]
    mag = jnp.abs(p) if absolute else jnp.maximum(p, 0.0)
    total = mag.sum(axis=3, keepdims=True)
    probs = jnp.where(total > 0, mag / jnp.where(total > 0, total, 1.0), 0.0)
    logits = jnp.where(probs > 0, jnp.log(probs), -jnp.inf)
    logits = jnp.moveaxis(logits, 3, -1)     # [N,Ho,Wo,C,K]
    choice = jax.random.categorical(key, logits, axis=-1)   # [N,Ho,Wo,C]
    onehot = jax.nn.one_hot(choice, ky * kx, axis=-1,
                            dtype=p.dtype)   # [N,Ho,Wo,C,K]
    any_mass = total > 0                     # [N,Ho,Wo,1,C]
    keep = jnp.moveaxis(onehot, -1, 3) * p * any_mass       # [N,Ho,Wo,K,C]
    # K is (ky, kx) row-major (see _patches) — invert the tiling
    y = keep.reshape(n, ho, wo, ky, kx, c).transpose(0, 1, 3, 2, 4, 5)
    y = y.reshape(n, ho * ky, wo * kx, c)
    pad_h, pad_w = h - ho * ky, w - wo * kx
    if pad_h or pad_w:
        y = jnp.pad(y, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
    return y


def depool(x, ky, kx):
    """Depooling: nearest-neighbor upsample by the window size (ref Znicz
    Depooling — decoder half of pooled autoencoders)."""
    return jnp.repeat(jnp.repeat(x, ky, axis=1), kx, axis=2)
