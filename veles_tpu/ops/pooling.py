"""Pooling ops (ref Znicz Max/Avg/MaxAbs/Stochastic pooling units,
SURVEY.md §2.9 "Pooling").  NHWC layout.

MaxAbsPooling keeps the *signed* value of the max-|x| element (Veles
semantics for tanh-centered activations).  Stochastic pooling draws the
kept element with probability proportional to |activation| (train-time
regularizer); its RNG is a jax key threaded from the unit's named stream,
so runs stay bit-reproducible."""

import jax
import jax.lax as lax
import jax.numpy as jnp


def _window(kx, ky, stride):
    sy, sx = stride if stride is not None else (ky, kx)
    return (1, ky, kx, 1), (1, sy, sx, 1)


def max_pool(x, ky, kx, stride=None, padding="VALID"):
    dims, strides = _window(kx, ky, stride)
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, padding)


def avg_pool(x, ky, kx, stride=None, padding="VALID"):
    dims, strides = _window(kx, ky, stride)
    s = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    ones = jnp.ones_like(x)
    n = lax.reduce_window(ones, 0.0, lax.add, dims, strides, padding)
    return s / n


def max_abs_pool(x, ky, kx, stride=None, padding="VALID"):
    """Signed value of the element with the largest |x| in each window."""
    dims, strides = _window(kx, ky, stride)

    def pick(a, b):
        return lax.select(lax.abs(a) >= lax.abs(b), a, b)

    return lax.reduce_window(x, 0.0, pick, dims, strides, padding)


def _patches(x, ky, kx, stride):
    """[N,Ho,Wo,ky*kx,C] view of pooling windows (general stride)."""
    n, h, w, c = x.shape
    sy, sx = stride if stride is not None else (ky, kx)
    ho = (h - ky) // sy + 1
    wo = (w - kx) // sx + 1
    idx_y = jnp.arange(ho)[:, None] * sy + jnp.arange(ky)[None, :]
    idx_x = jnp.arange(wo)[:, None] * sx + jnp.arange(kx)[None, :]
    # gather rows then cols; shapes [N,Ho,ky,W,C] -> [N,Ho,ky,Wo,kx,C]
    g = x[:, idx_y.reshape(-1), :, :].reshape(n, ho, ky, w, c)
    g = g[:, :, :, idx_x.reshape(-1), :].reshape(n, ho, ky, wo, kx, c)
    return g.transpose(0, 1, 3, 2, 4, 5).reshape(n, ho, wo, ky * kx, c)


def stochastic_pool(x, ky, kx, key, stride=None, absolute=False):
    """Zeiler-style stochastic pooling: sample one element per window with
    p ∝ activation (or |activation| for the Abs variant).  All-zero windows
    yield 0 (matching max-pool of zeros)."""
    p = _patches(x, ky, kx, stride)          # [N,Ho,Wo,K,C]
    mag = jnp.abs(p) if absolute else jnp.maximum(p, 0.0)
    total = mag.sum(axis=3, keepdims=True)
    probs = jnp.where(total > 0, mag / jnp.where(total > 0, total, 1.0), 0.0)
    # categorical over the window axis, per (n, ho, wo, c)
    logits = jnp.where(probs > 0, jnp.log(probs), -jnp.inf)
    logits = jnp.moveaxis(logits, 3, -1)      # [N,Ho,Wo,C,K]
    choice = jax.random.categorical(key, logits, axis=-1)  # [N,Ho,Wo,C]
    gathered = jnp.take_along_axis(
        jnp.moveaxis(p, 3, -1), choice[..., None], axis=-1)[..., 0]
    any_mass = jnp.moveaxis(total, 3, -1)[..., 0] > 0
    return jnp.where(any_mass, gathered, 0.0)


def stochastic_pool_infer(x, ky, kx, stride=None, absolute=False):
    """Inference-time stochastic pooling = probability-weighted average
    (Zeiler §3; what StochasticPooling computes when not training)."""
    p = _patches(x, ky, kx, stride)
    mag = jnp.abs(p) if absolute else jnp.maximum(p, 0.0)
    total = mag.sum(axis=3, keepdims=True)
    w = jnp.where(total > 0, mag / jnp.where(total > 0, total, 1.0), 0.0)
    return (p * w).sum(axis=3)


def depool(x, ky, kx):
    """Depooling: nearest-neighbor upsample by the window size (ref Znicz
    Depooling — decoder half of pooled autoencoders)."""
    return jnp.repeat(jnp.repeat(x, ky, axis=1), kx, axis=2)
