"""Precision policy (replaces ref precision_type/precision_level,
veles/config.py:246-248).

The reference offered double/float plus software-compensated summation
(Kahan=level 1, multipartial=level 2) inside its hand-written matmul kernels.
On TPU the idiomatic mapping is a dtype policy: inputs/weights in a compute
dtype (bfloat16 → MXU-native), accumulation and optimizer math in float32
(``preferred_element_type``), master params in float32.  ``precision_level``
>= 1 forces float32 compute — the "more precise, slower" knob with the same
contract as the reference's levels."""

import dataclasses

import jax.numpy as jnp

from veles_tpu.config import root


@dataclasses.dataclass(frozen=True)
class Policy:
    compute: jnp.dtype = jnp.bfloat16
    accum: jnp.dtype = jnp.float32
    param: jnp.dtype = jnp.float32

    def cast_in(self, x):
        return x.astype(self.compute)

    def cast_out(self, x):
        return x.astype(self.accum)


def default_policy():
    prec = root.common.engine.precision
    level = root.common.engine.get("precision_level", 0)
    compute = jnp.dtype(prec.get("compute", "bfloat16"))
    if level >= 1:
        compute = jnp.dtype("float32")
    return Policy(compute=compute,
                  accum=jnp.dtype(prec.get("accum", "float32")),
                  param=jnp.dtype(prec.get("param", "float32")))
