"""Recurrent ops — LSTM and simple RNN (ref: Veles RNN/LSTM support,
'in progress' in the reference — manualrst_veles_algorithms.rst:105-112;
completed here as first-class layer types).

TPU shape: the time loop is a ``lax.scan`` whose body is one fused
[B, in+hidden] × [in+hidden, 4·hidden] matmul — gate math rides the MXU,
XLA pipelines the scan.  Inputs are [B, T, F]."""

import jax
import jax.numpy as jnp

from veles_tpu.ops.policy import Policy


def lstm_init(rng, n_in, n_hidden, dtype=jnp.float32):
    s = (n_in + n_hidden) ** -0.5
    w = rng.fill_uniform((n_in + n_hidden, 4 * n_hidden), s)
    b = jnp.zeros((4 * n_hidden,), dtype)
    # forget-gate bias 1.0: standard trick to keep early gradients alive
    b = b.at[n_hidden:2 * n_hidden].set(1.0)
    return {"weights": jnp.asarray(w, dtype), "bias": b}


def lstm_forward(params, x, policy=Policy(), return_sequences=False):
    """x: [B, T, F] → [B, H] (last hidden) or [B, T, H]."""
    w = policy.cast_in(params["weights"])
    b = params["bias"].astype(policy.accum)
    n_hidden = b.shape[0] // 4
    batch = x.shape[0]
    h0 = jnp.zeros((batch, n_hidden), policy.accum)
    c0 = jnp.zeros((batch, n_hidden), policy.accum)

    def step(carry, xt):
        h, c = carry
        z = jnp.dot(policy.cast_in(jnp.concatenate([xt, h], axis=1)), w,
                    preferred_element_type=policy.accum) + b
        i, f, g, o = jnp.split(z, 4, axis=1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h

    (h_last, _), h_seq = jax.lax.scan(step, (h0, c0),
                                      jnp.swapaxes(x, 0, 1))
    if return_sequences:
        return jnp.swapaxes(h_seq, 0, 1)
    return h_last


def rnn_init(rng, n_in, n_hidden, dtype=jnp.float32):
    s = (n_in + n_hidden) ** -0.5
    w = rng.fill_uniform((n_in + n_hidden, n_hidden), s)
    return {"weights": jnp.asarray(w, dtype),
            "bias": jnp.zeros((n_hidden,), dtype)}


def rnn_forward(params, x, policy=Policy(), return_sequences=False):
    """Simple tanh RNN; same shapes as lstm_forward."""
    w = policy.cast_in(params["weights"])
    b = params["bias"].astype(policy.accum)
    n_hidden = b.shape[0]
    h0 = jnp.zeros((x.shape[0], n_hidden), policy.accum)

    def step(h, xt):
        z = jnp.dot(policy.cast_in(jnp.concatenate([xt, h], axis=1)), w,
                    preferred_element_type=policy.accum) + b
        h = jnp.tanh(z)
        return h, h

    h_last, h_seq = jax.lax.scan(step, h0, jnp.swapaxes(x, 0, 1))
    if return_sequences:
        return jnp.swapaxes(h_seq, 0, 1)
    return h_last
