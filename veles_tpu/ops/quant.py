"""Weight-only int8 serving quantization (W8A8-dynamic).

Decode is weight-bandwidth-bound: at batch ≤ ~32 every step streams the
whole parameter set from HBM while the MXU idles, so halving the weight
bytes (int8 vs bf16) is worth up to 2× decode throughput on chip.  A
naive "dequantize to bf16 inside the step" cannot deliver that — XLA
hoists the loop-invariant convert out of the decode scan and the loop
reads bf16 again.  The matmuls here therefore keep the weights int8 all
the way into the dot: activations are quantized per row on the fly
(dynamic symmetric), the MXU runs its native int8×int8 → int32 path,
and one f32 rescale (row scale ⊗ column scale) restores the magnitude.

Scheme matches the int8 export packages (services/export.py:31-56,
consumed by the C++ runtime): symmetric, per-output-channel scales,
round-to-nearest, clip ±127.  Embedding tables quantize PER ROW so the
same tensor serves both directions exactly: a gathered row dequantizes
by its own scalar, and the tied LM head (x @ tableᵀ) treats the row
scales as output-channel scales.  (KV-cache int8 lives in
ops.attention.QuantCache; the reference has no serving quantization at
all — its closest analog is the fp16→fp32 load transform in
libVeles/src/numpy_array_loader.cc.)
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantWeight(NamedTuple):
    """int8 payload + f32 scales.  A NamedTuple, so it is a pytree and
    flows through jit/scan/device_put untouched."""
    q: jnp.ndarray        # int8  [n_in, n_out]   (tables: [V, d])
    scale: jnp.ndarray    # f32   [n_out]         (tables: [V])


def symmetric_int8(x, axis=-1, keepdims=True, eps=1e-8):
    """THE symmetric int8 quantizer — weights, activations and the KV
    cache (ops.attention.quantize_kv) all route through this one
    function so the scheme can never drift between them:
    ``scale = max(max|x|, eps) / 127`` over ``axis``, round-to-nearest,
    clip ±127."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=axis, keepdims=True),
                        eps) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, (scale if keepdims else jnp.squeeze(scale, axis))


def quantize_weight(w, axis=0):
    """Symmetric per-channel int8: scales reduced over ``axis`` (the
    contraction dim — 0 for an [in, out] weight; 1 for a [V, d] table,
    giving per-row scales)."""
    q, s = symmetric_int8(jnp.asarray(w), axis=axis, keepdims=False)
    return QuantWeight(q, s)


def _quant_acts(x):
    """Dynamic per-row activation quantization (the A8 half of W8A8)."""
    return symmetric_int8(x)


def int8_matmul(x, qw):
    """``x @ W`` for an [in, out] QuantWeight: int8×int8 dot with int32
    accumulation, rescaled to f32.  Output shape x.shape[:-1] + (out,)."""
    xq, xs = _quant_acts(x)
    y = jax.lax.dot_general(xq, qw.q, (((x.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * xs * qw.scale


def int8_matmul_t(x, qw):
    """``x @ Wᵀ`` for a per-row-quantized [V, d] table — the tied-LM-head
    direction (row scales act as output-channel scales)."""
    xq, xs = _quant_acts(x)
    y = jax.lax.dot_general(xq, qw.q, (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * xs * qw.scale


def take_rows(qw, idx):
    """Embedding lookup on a per-row-quantized table: gather int8 rows,
    dequantize only what was gathered (exact — one scalar per row)."""
    rows = jnp.take(qw.q, idx, axis=0).astype(jnp.float32)
    return rows * jnp.take(qw.scale, idx)[..., None]


#: weight keys the serve path consumes through the QuantWeight-aware
#: funnels (attention._proj, linear.matmul, TiedLMHead.apply) — an
#: explicit allowlist so unrelated layer state can never be quantized
#: by accident
_MHA_KEYS = ("wq", "wk", "wv", "wo")
_DENSE_KEYS = ("w1", "w2", "weights")


def quantize_lm_params(params, embed_name=None):
    """Map a trained transformer-LM param tree to the int8 serving
    layout: attention projections and FFN/head matrices per-output-
    channel, the embedding table (``embed_name``) per row; biases,
    layer norms, positional tables and anything unrecognized stay
    untouched."""
    out = {}
    for lname, sub in params.items():
        if not isinstance(sub, dict):
            out[lname] = sub
            continue
        new = {}
        for k, v in sub.items():
            if k == "mha" and isinstance(v, dict):
                new[k] = {mk: (quantize_weight(mv)
                               if mk in _MHA_KEYS else mv)
                          for mk, mv in v.items()}
            elif k == "table" and lname == embed_name:
                new[k] = quantize_weight(v, axis=1)
            elif k in _DENSE_KEYS and getattr(v, "ndim", 0) == 2:
                new[k] = quantize_weight(v)
            else:
                new[k] = v
        out[lname] = new
    return out
