"""Weight-only int8/w4a8 serving quantization.

Decode is weight-bandwidth-bound: at batch ≤ ~32 every step streams the
whole parameter set from HBM while the MXU idles, so halving the weight
bytes (int8 vs bf16) is worth up to 2× decode throughput on chip.  A
naive "dequantize to bf16 inside the step" cannot deliver that — XLA
hoists the loop-invariant convert out of the decode scan and the loop
reads bf16 again.  The matmuls here therefore keep the weights int8 all
the way into the dot: activations are quantized per row on the fly
(dynamic symmetric), the MXU runs its native int8×int8 → int32 path,
and one f32 rescale (row scale ⊗ column scale) restores the magnitude.

Scheme matches the int8 export packages (services/export.py:31-56,
consumed by the C++ runtime): symmetric, per-output-channel scales,
round-to-nearest, clip ±127.  Embedding tables quantize PER ROW so the
same tensor serves both directions exactly: a gathered row dequantizes
by its own scalar, and the tied LM head (x @ tableᵀ) treats the row
scales as output-channel scales.  (KV-cache int8 lives in
ops.attention.QuantCache; the reference has no serving quantization at
all — its closest analog is the fp16→fp32 load transform in
libVeles/src/numpy_array_loader.cc.)

w4a8 (``QuantWeight4``) halves the payload again: symmetric int4
weights nibble-packed two-per-byte along the contraction axis, int8
dynamic activations, and the dot accumulates in f32 (there is no
native int4×int8 MXU path; the operands are integer-valued so the
float dot is exact, and the win is the 0.5 B/param payload).  One
honest caveat, measured-not-assumed on the next TPU window: XLA may
hoist the loop-invariant nibble unpack out of the decode scan, in
which case the per-step stream falls back to int8-equivalent bytes
while resident memory stays halved — the jaxpr audit below pins only
that no dequantized FLOAT copy leaves the dots.

``stray_dequant_sites`` is the audit that keeps all of this true: it
scans a decode step's jaxpr for payload-sized int8→float conversions
that do not feed a ``dot_general``, i.e. the exact hoistable
dense-dequant bug class the int8 path was built to avoid.  The serving
tests trace the real decode/tick functions through it.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantWeight(NamedTuple):
    """int8 payload + f32 scales.  A NamedTuple, so it is a pytree and
    flows through jit/scan/device_put untouched."""
    q: jnp.ndarray        # int8  [n_in, n_out]   (tables: [V, d])
    scale: jnp.ndarray    # f32   [n_out]         (tables: [V])


@jax.tree_util.register_pytree_node_class
class QuantWeight4(object):
    """Nibble-packed int4 payload + f32 per-channel scales (w4a8).

    ``q`` packs two int4 values per int8 byte along ``axis`` (the
    contraction axis — 0 for an [in, out] weight, 1 for a [V, d]
    table): byte ``i`` holds logical entries ``2i`` (low nibble) and
    ``2i + 1`` (high nibble), two's complement.  ``n`` is the logical
    length of the packed axis (odd lengths pad one zero nibble).
    Registered as a pytree with (q, scale) as leaves and (n, axis)
    static, so it flows through jit/scan/device_put like QuantWeight.
    """

    def __init__(self, q, scale, n, axis):
        self.q = q
        self.scale = scale
        self.n = int(n)
        self.axis = int(axis)

    def tree_flatten(self):
        return (self.q, self.scale), (self.n, self.axis)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    def __repr__(self):
        return ("QuantWeight4(q=%r, scale=%r, n=%d, axis=%d)"
                % (getattr(self.q, "shape", None),
                   getattr(self.scale, "shape", None), self.n,
                   self.axis))


def symmetric_int8(x, axis=-1, keepdims=True, eps=1e-8):
    """THE symmetric int8 quantizer — weights, activations and the KV
    cache (ops.attention.quantize_kv) all route through this one
    function so the scheme can never drift between them:
    ``scale = max(max|x|, eps) / 127`` over ``axis``, round-to-nearest,
    clip ±127."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=axis, keepdims=True),
                        eps) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, (scale if keepdims else jnp.squeeze(scale, axis))


def quantize_weight(w, axis=0):
    """Symmetric per-channel int8: scales reduced over ``axis`` (the
    contraction dim — 0 for an [in, out] weight; 1 for a [V, d] table,
    giving per-row scales)."""
    q, s = symmetric_int8(jnp.asarray(w), axis=axis, keepdims=False)
    return QuantWeight(q, s)


def _pack_nibbles(q, axis):
    """int8 values in [-8, 7] → one int8 byte per PAIR along ``axis``
    (low nibble = even index, high nibble = odd; two's complement).
    Odd lengths pad a zero nibble."""
    q = jnp.moveaxis(q, axis, 0)
    if q.shape[0] % 2:
        q = jnp.concatenate([q, jnp.zeros((1,) + q.shape[1:], q.dtype)])
    lo, hi = q[0::2], q[1::2]
    packed = (lo & jnp.int8(0x0F)) | jnp.left_shift(hi, 4)
    return jnp.moveaxis(packed.astype(jnp.int8), 0, axis)


def _unpack_nibbles(p, n, axis):
    """Inverse of ``_pack_nibbles``: int8 bytes → int8 values (sign-
    extended nibbles), trimmed to the logical length ``n``."""
    p = jnp.moveaxis(p, axis, 0)
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)    # arithmetic >>
    hi = jnp.right_shift(p, 4)
    q = jnp.stack([lo, hi], axis=1).reshape((-1,) + p.shape[1:])[:n]
    return jnp.moveaxis(q, 0, axis)


def quantize_weight4(w, axis=0):
    """Symmetric per-channel int4 (w4a8's weight half): scales over
    ``axis`` at max|w|/7, round-to-nearest, clip ±7, nibble-packed
    along ``axis``."""
    w = jnp.asarray(w)
    xf = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=axis, keepdims=True),
                        1e-8) / 7.0
    q = jnp.clip(jnp.round(xf / scale), -7, 7).astype(jnp.int8)
    return QuantWeight4(_pack_nibbles(q, axis),
                        jnp.squeeze(scale, axis), w.shape[axis], axis)


def _quant_acts(x):
    """Dynamic per-row activation quantization (the A8 half of W8A8)."""
    return symmetric_int8(x)


def int8_matmul(x, qw):
    """``x @ W`` for an [in, out] QuantWeight: int8×int8 dot with int32
    accumulation, rescaled to f32.  Output shape x.shape[:-1] + (out,)."""
    xq, xs = _quant_acts(x)
    y = jax.lax.dot_general(xq, qw.q, (((x.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * xs * qw.scale


def int8_matmul_t(x, qw):
    """``x @ Wᵀ`` for a per-row-quantized [V, d] table — the tied-LM-head
    direction (row scales act as output-channel scales)."""
    xq, xs = _quant_acts(x)
    y = jax.lax.dot_general(xq, qw.q, (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * xs * qw.scale


def w4a8_matmul(x, qw):
    """``x @ W`` for an [in, out] QuantWeight4: int8 dynamic
    activations × unpacked int4 weights, f32 accumulation (both
    operands are integer-valued, so the float dot is exact).  The
    nibble unpack and the f32 convert feed the dot DIRECTLY — no
    dequantized weight copy is ever built outside it (pinned by
    ``stray_dequant_sites``)."""
    xq, xs = _quant_acts(x)
    w = _unpack_nibbles(qw.q, qw.n, 0).astype(jnp.float32)
    y = jax.lax.dot_general(xq.astype(jnp.float32), w,
                            (((x.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return y * xs * qw.scale


def w4a8_matmul_t(x, qw):
    """``x @ Wᵀ`` for a per-row-quantized [V, d] QuantWeight4 table
    (packed along d) — the tied-LM-head direction."""
    xq, xs = _quant_acts(x)
    w = _unpack_nibbles(qw.q, qw.n, 1).astype(jnp.float32)
    y = jax.lax.dot_general(xq.astype(jnp.float32), w,
                            (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return y * xs * qw.scale


def is_quant(w):
    """True for any serving-quantized weight leaf (int8 or w4a8)."""
    return isinstance(w, (QuantWeight, QuantWeight4))


def quant_matmul(x, qw):
    """``x @ W`` routed by scheme — THE funnel the serve-path matmul
    sites (attention._proj, linear.matmul) call so int8 and w4a8 can
    never diverge per call site."""
    if isinstance(qw, QuantWeight4):
        return w4a8_matmul(x, qw)
    return int8_matmul(x, qw)


def quant_matmul_t(x, qw):
    """``x @ Wᵀ`` routed by scheme (the tied-LM-head funnel)."""
    if isinstance(qw, QuantWeight4):
        return w4a8_matmul_t(x, qw)
    return int8_matmul_t(x, qw)


def take_rows(qw, idx):
    """Embedding lookup on a per-row-quantized table: gather the
    payload rows, dequantize only what was gathered (exact — one
    scalar per row; w4a8 tables gather packed rows and unpack the
    gathered bytes only)."""
    if isinstance(qw, QuantWeight4):
        packed = jnp.take(qw.q, idx, axis=0)
        rows = _unpack_nibbles(packed, qw.n, packed.ndim - 1).astype(
            jnp.float32)
    else:
        rows = jnp.take(qw.q, idx, axis=0).astype(jnp.float32)
    return rows * jnp.take(qw.scale, idx)[..., None]


#: weight keys the serve path consumes through the QuantWeight-aware
#: funnels (attention._proj, linear.matmul, TiedLMHead.apply) — an
#: explicit allowlist so unrelated layer state can never be quantized
#: by accident
_MHA_KEYS = ("wq", "wk", "wv", "wo")
_DENSE_KEYS = ("w1", "w2", "weights")


def quantize_lm_params(params, embed_name=None, scheme="int8"):
    """Map a trained transformer-LM param tree to the quantized serving
    layout: attention projections and FFN/head matrices per-output-
    channel, the embedding table (``embed_name``) per row; biases,
    layer norms, positional tables and anything unrecognized stay
    untouched.  ``scheme``: ``"int8"`` (W8A8-dynamic, QuantWeight) or
    ``"w4a8"`` (nibble-packed int4 payload, QuantWeight4)."""
    if scheme not in ("int8", "w4a8"):
        raise ValueError("scheme must be 'int8' or 'w4a8', got %r"
                         % (scheme,))
    qfn = quantize_weight if scheme == "int8" else quantize_weight4
    out = {}
    for lname, sub in params.items():
        if not isinstance(sub, dict):
            out[lname] = sub
            continue
        new = {}
        for k, v in sub.items():
            if k == "mha" and isinstance(v, dict):
                new[k] = {mk: (qfn(mv)
                               if mk in _MHA_KEYS else mv)
                          for mk, mv in v.items()}
            elif k == "table" and lname == embed_name:
                new[k] = qfn(v, axis=1)
            elif k in _DENSE_KEYS and getattr(v, "ndim", 0) == 2:
                new[k] = qfn(v)
            else:
                new[k] = v
        out[lname] = new
    return out


# --------------------------------------------------------------------------
# Stray-dequant jaxpr audit: no quantized payload may be dequantized
# outside a dot on the decode hot path.
# --------------------------------------------------------------------------

#: primitives a payload-sized float convert may flow through on its way
#: into a dot operand — pure layout moves XLA fuses into the MXU load
#: path.  Anything else (a scale multiply, an add, a scatter) means a
#: dense dequantized copy was materialized outside the dot.
_LAYOUT_PRIMS = frozenset(("reshape", "transpose", "broadcast_in_dim",
                           "squeeze", "copy"))

_SUB_JAXPR_PARAMS = ("jaxpr", "call_jaxpr", "body_jaxpr", "cond_jaxpr",
                     "branches")


def _sub_jaxprs(eqn):
    for key in _SUB_JAXPR_PARAMS:
        v = eqn.params.get(key)
        if v is None:
            continue
        for item in (v if isinstance(v, (tuple, list)) else (v,)):
            yield getattr(item, "jaxpr", item)


def stray_dequant_sites(closed_jaxpr, min_elems):
    """Scan a (closed) jaxpr — recursively through scan/cond/while/pjit
    bodies — for int8→float ``convert_element_type`` ops of size >=
    ``min_elems`` whose result does NOT feed a ``dot_general``
    (directly or through pure layout primitives).

    That is exactly the hoistable dense-dequant bug class: a
    payload-sized float copy of a quantized weight materialized outside
    the dot gets hoisted out of the decode scan by XLA, and the loop
    streams floats again.  Gathered-row dequants (embedding lookups)
    fall under ``min_elems`` and pass.  Returns a list of description
    strings (empty = clean); the serving tests assert it empty over the
    traced int8/w4a8 decode step."""
    sites = []

    def feeds_dot_only(var, consumers, depth=0):
        eqns = consumers.get(id(var), ())
        if not eqns:
            # unused converts don't stream anything; XLA dead-codes them
            return True
        for e in eqns:
            if e.primitive.name == "dot_general":
                continue
            if e.primitive.name in _LAYOUT_PRIMS and depth < 4:
                if all(feeds_dot_only(o, consumers, depth + 1)
                       for o in e.outvars):
                    continue
            return False
        return True

    def walk(jaxpr):
        consumers = {}
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                if hasattr(v, "aval") and not hasattr(v, "val"):
                    consumers.setdefault(id(v), []).append(eqn)
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "convert_element_type":
                iv = eqn.invars[0]
                aval = getattr(iv, "aval", None)
                out = eqn.outvars[0].aval
                if (aval is not None
                        and aval.dtype == jnp.int8
                        and jnp.issubdtype(out.dtype, jnp.floating)
                        and aval.size >= min_elems
                        and not feeds_dot_only(eqn.outvars[0],
                                               consumers)):
                    sites.append(
                        "int8%s -> %s%s convert of %d elems does not "
                        "feed a dot_general"
                        % (tuple(aval.shape), out.dtype.name,
                           tuple(out.shape), aval.size))
            for sub in _sub_jaxprs(eqn):
                walk(sub)

    walk(getattr(closed_jaxpr, "jaxpr", closed_jaxpr))
    return sites


def min_payload_elems(params):
    """Smallest quantized-payload element count in a param tree — the
    ``min_elems`` threshold for ``stray_dequant_sites`` (anything at or
    above it is a whole-weight dequant; gathered rows sit far below)."""
    sizes = []

    def visit(leaf):
        if isinstance(leaf, QuantWeight):
            sizes.append(int(leaf.q.size))
        elif isinstance(leaf, QuantWeight4):
            # LOGICAL int4 count, not bytes*2: an odd packed axis pads
            # one nibble, and a dense dequant of the weight is exactly
            # n * channels elements — the threshold must not sit above
            # the very convert it exists to catch
            packed = -(-leaf.n // 2)
            sizes.append(int(leaf.q.size) // packed * leaf.n)

    jax.tree_util.tree_map(visit, params, is_leaf=is_quant)
    if not sizes:
        raise ValueError("param tree holds no quantized weights")
    return min(sizes)
