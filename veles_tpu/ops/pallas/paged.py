"""Paged-KV decode attention as a Pallas TPU kernel.

The paged continuous batcher (models/generate.py PagedContinuousBatcher)
keeps every slot's KV cache in a SHARED block pool addressed through
per-slot block tables.  Its first implementation gathered each row's
blocks into a dense [B, H, T, hd] view every tick, ran the dense decode
core, and scattered one position back — ~2x cache traffic vs dense
slots, measured as a ~20% serving-throughput tax on silicon
(docs/perf.md).  This kernel erases the gather: the block table rides
the grid as a SCALAR-PREFETCH argument, so each (batch, kv-head,
block) grid step DMAs its K/V tile straight from the pool block the
table names — the classic paged-attention move (Kwon et al. 2023)
recast for the TPU: instead of pointer-chasing inside the kernel,
Pallas's prefetched index_map picks the pool block per grid step and
Mosaic pipelines the HBM→VMEM copies.

Reads are exactly the live blocks (dead table entries all point at the
reserved dummy block 0, so their copies collapse to one reusable tile
and their scores are masked), and only up to each row's own length —
dense decode by contrast streams every slot's full max_len.

Layout contract (matches PagedContinuousBatcher):
  q      [B, Hq, hd]        query at the position being decoded (rope
                            already applied), Hq = G * Hkv
  pool_k [1+P, Hkv, bs, hd] block 0 reserved as the dummy target
  pool_v [1+P, Hkv, bs, hd]
  table  [B, nbm] int32     per-row pool-block ids (0 = unallocated)
  pos    [B] int32          per-row position just written; keys
                            0..pos[b] inclusive are live
  -> out [B, Hq, hd]

Ground truth: ``paged_attention_reference`` (the gather formulation) —
the tests pin kernel == reference; off-TPU the kernel runs in interpret
mode like every kernel in this package.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.pallas import autodetect_interpret, register_kernel_audit

NEG_INF = -1e30
_LANES = 128

#: min sublane tile for the q block: bf16 wants 16 rows, f32 8 — 16
#: covers both, and the padded rows cost nothing measurable at decode
#: (the kernel is HBM-bound on the K/V stream, not the tiny q tile)
_MIN_G = 16


def _decode_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                   acc, m, l, *, scale, bs, nbm):
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, NEG_INF)
        l[:] = jnp.zeros_like(l)

    # a block whose first key is already past the row's position is
    # fully dead: skip the whole update (its table entry is 0, so the
    # DMA re-reads the one dummy tile — bandwidth-free after block 0)
    @pl.when(i * bs <= pos_ref[b])
    def _():
        s = jax.lax.dot_general(
            q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        kpos = i * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = kpos <= pos_ref[b]
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(m_prev <= NEG_INF / 2, 0.0, corr)
        l[:] = l[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l.shape)
        m[:] = jnp.broadcast_to(m_new, m.shape)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nbm - 1)
    def _():
        o_ref[0, 0] = (acc[:] / jnp.maximum(l[:, :1], 1e-30)).astype(
            o_ref.dtype)


def _decode_kernel_quant(table_ref, pos_ref, q_ref, k_ref, ks_ref,
                         v_ref, vs_ref, o_ref, acc, m, l, *, scale, bs,
                         nbm):
    """Quantized-pool flavor: the K/V tiles arrive int8 (HBM streams
    one byte per element — the whole point) with per-position f32
    scales, and are dequantized IN KERNEL, in VMEM, with f32
    accumulation throughout.  The scales fold in after the dots
    exactly like the dense QuantCache einsums in ops.attention
    (q·(k·s) == (q·k)·s per position), so the math is the gather
    tick's, just narrower on the wire."""
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, NEG_INF)
        l[:] = jnp.zeros_like(l)

    @pl.when(i * bs <= pos_ref[b])
    def _():
        s = jax.lax.dot_general(
            q_ref[0, 0].astype(jnp.float32),
            k_ref[0, 0].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # per-position k scales, then the 1/sqrt(hd) logit scale
        s = s * ks_ref[0, 0][:, 0][None, :] * scale
        kpos = i * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = kpos <= pos_ref[b]
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(m_prev <= NEG_INF / 2, 0.0, corr)
        l[:] = l[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l.shape)
        m[:] = jnp.broadcast_to(m_new, m.shape)
        # fold the per-position v scales into the probabilities (the
        # QuantCache move), keep the accumulate f32
        pv = p * vs_ref[0, 0][:, 0][None, :]
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            pv, v_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nbm - 1)
    def _():
        o_ref[0, 0] = (acc[:] / jnp.maximum(l[:, :1], 1e-30)).astype(
            o_ref.dtype)


def _resolve_block_g(g, hd, dtype, block_g=None):
    """Resolve the q-group sublane pad: explicit argument > site config
    (``root.common.serve.paged_block_g``) > autotuner winner
    (``veles_tpu.tuner``, kernel ``paged.decode``) > ``_MIN_G``.  Always
    clamped to hold the real group (>= g) and the sublane tile.
    Reachable from the audit hook and every decode trace, so a
    non-integer config value falls through to the tuner instead of
    raising (same contract as :func:`preferred_pool_block`)."""
    if block_g is None:
        from veles_tpu.config import root
        cfg = root.common.get("serve", {})
        block_g = (cfg or {}).get("paged_block_g") if cfg else None
    try:
        block_g = int(block_g or 0)
    except (TypeError, ValueError):      # "auto", garbage
        block_g = 0
    if not block_g:
        try:
            from veles_tpu import tuner
            win = tuner.lookup("paged.decode",
                               tuner.paged_shape_key(hd, g), dtype)
            if win:
                block_g = int(win.get("block_g") or 0)
        except Exception:  # noqa: BLE001 — tuning is advisory
            pass
    return max(int(block_g or 0), g, _MIN_G)


def preferred_pool_block(hd, g=1, dtype=jnp.bfloat16, default=16):
    """The KV pool block size serving should allocate when the caller
    did not pin one: site config (``root.common.serve.paged_block``) >
    autotuner winner > ``default``.  The pool layout is decided at
    admission time by PagedContinuousBatcher — the kernel then simply
    follows whatever block the pool was built with, so THIS is the
    point where a tuned ``paged.decode`` block takes effect.  The
    config value goes through the ONE ``serve.paged_block`` grammar
    (``models.generate.parse_paged_block`` — shared with the engine,
    so the audit hook and serving can never disagree): only an
    explicit positive block pins; ``"auto"``/``-1``/off-values/garbage
    fall through to the tuner — this is reachable from the lint's
    audit hook, so it must never raise on any config value."""
    from veles_tpu.config import root
    cfg = root.common.get("serve", {})
    pinned = (cfg or {}).get("paged_block") if cfg else None
    if pinned is not None:
        try:
            from veles_tpu.models.generate import parse_paged_block
            _, block = parse_paged_block(pinned)
        except (TypeError, ValueError):  # garbage ("fast", [1], ...)
            block = None
        if block:
            return int(block)
    try:
        from veles_tpu import tuner
        win = tuner.lookup("paged.decode", tuner.paged_shape_key(hd, g),
                           dtype)
        if win and win.get("block"):
            return int(win["block"])
    except Exception:  # noqa: BLE001 — tuning is advisory
        pass
    # untuned fallback is sublane-aware: int8 pools (QuantCache) need
    # 32-row tiles on real silicon, bf16/f32 keep the historical 16
    from veles_tpu.ops.pallas import mosaic_sublane_min
    return max(int(default), mosaic_sublane_min(dtype))


def paged_attention_decode(q, pool_k, pool_v, table, pos, scale=None,
                           interpret=None, block_g=None):
    """One decode step of attention over a paged KV pool (see module
    docstring for the layout contract).  Returns [B, Hq, hd].

    ``pool_k``/``pool_v`` may be plain arrays OR
    ``ops.attention.QuantCache`` pairs (int8 data [1+P, Hkv, bs, hd] +
    f32 per-position scales [1+P, Hkv, bs, 1]) — the quantized pool
    streams one byte per KV element from HBM and dequantizes in
    kernel with f32 accumulation (``_decode_kernel_quant``).

    ``block_g`` — the q-group sublane pad (rows per grid step); unset,
    it resolves through config > autotuner > ``_MIN_G``; quantized
    pools key the tuner lookup by the POOL dtype (int8), matching how
    ``tuner.sweeps.sweep_paged(dtype="int8")`` records winners."""
    from veles_tpu.ops.attention import QuantCache
    quant = isinstance(pool_k, QuantCache)
    kd = pool_k.data if quant else pool_k
    b, hq, hd = q.shape
    npool, hkv, bs, _ = kd.shape
    nbm = table.shape[1]
    if hq % hkv:
        raise ValueError("Hq %d %% Hkv %d != 0" % (hq, hkv))
    g = hq // hkv
    gp = _resolve_block_g(g, hd, kd.dtype if quant else q.dtype,
                          block_g)
    scale = (hd ** -0.5) if scale is None else scale

    # [B, Hq, hd] -> [B, Hkv, Gp, hd]: group queries under their kv
    # head; pad the group dim up to the sublane tile (padded rows carry
    # zeros — their softmax is uniform over live keys, finite, and the
    # rows are sliced off below)
    qg = q.reshape(b, hkv, g, hd)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

    def at_q(bi, h, i, tbl, ps):
        return (bi, h, 0, 0)

    def at_pool(bi, h, i, tbl, ps):
        return (tbl[bi, i], h, 0, 0)

    if quant:
        kernel = functools.partial(_decode_kernel_quant, scale=scale,
                                   bs=bs, nbm=nbm)
        in_specs = [
            pl.BlockSpec((1, 1, gp, hd), at_q),
            pl.BlockSpec((1, 1, bs, hd), at_pool),   # k int8
            pl.BlockSpec((1, 1, bs, 1), at_pool),    # k scales
            pl.BlockSpec((1, 1, bs, hd), at_pool),   # v int8
            pl.BlockSpec((1, 1, bs, 1), at_pool),    # v scales
        ]
        operands = (qg, pool_k.data, pool_k.scale, pool_v.data,
                    pool_v.scale)
    else:
        kernel = functools.partial(_decode_kernel, scale=scale, bs=bs,
                                   nbm=nbm)
        in_specs = [
            pl.BlockSpec((1, 1, gp, hd), at_q),
            pl.BlockSpec((1, 1, bs, hd), at_pool),
            pl.BlockSpec((1, 1, bs, hd), at_pool),
        ]
        operands = (qg, pool_k, pool_v)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, hkv, nbm),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, gp, hd), at_q),
            scratch_shapes=[
                pltpu.VMEM((gp, hd), jnp.float32),
                pltpu.VMEM((gp, _LANES), jnp.float32),
                pltpu.VMEM((gp, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, hd), q.dtype),
        interpret=autodetect_interpret(interpret),
    )(table.astype(jnp.int32), pos.astype(jnp.int32), *operands)
    return out[:, :, :g].reshape(b, hq, hd)


def paged_attention_reference(q, pool_k, pool_v, table, pos,
                              scale=None):
    """Gather-formulation ground truth (identical math to the dense
    decode einsum in ops.attention.mha_step): materialize each row's
    blocks densely, run a masked softmax.  QuantCache pools
    dequantize the gathered view (data × per-position scale — exactly
    what the in-kernel fold computes).  Used by the tests and as the
    documentation of the kernel's exact semantics."""
    from veles_tpu.ops.attention import QuantCache
    b, hq, hd = q.shape
    kd = pool_k.data if isinstance(pool_k, QuantCache) else pool_k
    _, hkv, bs, _ = kd.shape
    nbm = table.shape[1]
    g = hq // hkv
    scale = (hd ** -0.5) if scale is None else scale

    def dense(pool):
        if isinstance(pool, QuantCache):
            v = (pool.data[table].astype(jnp.float32)
                 * pool.scale[table])         # [B, nbm, Hkv, bs, hd]
        else:
            v = pool[table]                   # [B, nbm, Hkv, bs, hd]
        v = jnp.moveaxis(v, 2, 1)             # [B, Hkv, nbm, bs, hd]
        return v.reshape(b, hkv, nbm * bs, hd)

    k = dense(pool_k)
    v = dense(pool_v)
    qg = q.reshape(b, hkv, g, hd)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, k,
                   preferred_element_type=jnp.float32) * scale
    live = (jnp.arange(nbm * bs)[None, None, None, :]
            <= pos[:, None, None, None])
    s = jnp.where(live, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgt,bktd->bkgd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.reshape(b, hq, hd).astype(q.dtype)


# --------------------------------------------------------------------------
# VP6xx launch-audit hook (analysis.numerics_audit): the decode
# kernel's launch geometry as data — pure arithmetic, nothing traced.
# --------------------------------------------------------------------------

def audit_launch(hd, bs, g=1, dtype=jnp.bfloat16, nbm=32, masked=True,
                 checked=(), q_dtype=jnp.bfloat16):
    """Launch description for one paged-decode configuration.  ``bs``
    is the KV pool block (PagedContinuousBatcher ``block``), ``g`` the
    query-group size (Hq/Hkv) — padded to the sublane tile exactly as
    ``paged_attention_decode`` does.  ``dtype`` is the POOL dtype:
    int8 describes the quantized-pool kernel variant (int8 K/V tiles +
    f32 per-position scale tiles, float q/out in ``q_dtype``)."""
    import numpy as np
    gp = max(g, _MIN_G)
    quant = np.dtype(dtype) == np.dtype(np.int8)
    if quant:
        blocks = [("q", (1, 1, gp, hd), q_dtype, {"full_lane": True}),
                  ("k", (1, 1, bs, hd), dtype, {"full_lane": True}),
                  ("k_scale", (1, 1, bs, 1), jnp.float32,
                   {"full_lane": True}),
                  ("v", (1, 1, bs, hd), dtype, {"full_lane": True}),
                  ("v_scale", (1, 1, bs, 1), jnp.float32,
                   {"full_lane": True}),
                  ("o", (1, 1, gp, hd), q_dtype, {"full_lane": True})]
    else:
        blocks = [("q", (1, 1, gp, hd), dtype, {"full_lane": True}),
                  ("k", (1, 1, bs, hd), dtype, {"full_lane": True}),
                  ("v", (1, 1, bs, hd), dtype, {"full_lane": True}),
                  ("o", (1, 1, gp, hd), dtype, {"full_lane": True})]
    return [{
        "kernel": "paged.decode.q8" if quant else "paged.decode",
        "masked": masked, "checked": checked,
        "blocks": blocks,
        "scratch": [("acc", (gp, hd), jnp.float32),
                    ("m", (gp, _LANES), jnp.float32),
                    ("l", (gp, _LANES), jnp.float32)],
        # every row reads up to its own length; dead blocks hit the
        # reserved dummy block and their scores are masked
        "grid_axes": [("pool-blocks", nbm * bs, bs)],
    }]


@register_kernel_audit("paged")
def _configured_launches():
    """What ``--serve`` with paged KV would actually launch at the
    flagship head dim: the pool block through the same config > tuner >
    default chain the batcher uses (:func:`preferred_pool_block`), the
    q-group pad through :func:`_resolve_block_g` — so an over-budget
    tuned winner fails the lint exactly like a hand-misconfigured
    ``paged_block``.  BOTH pool flavors are audited: the bf16 pool and
    the int8 (``cache_dtype="int8"``) QuantCache pool, each resolved
    at its own dtype key."""
    hd, g = 128, 1
    launches = []
    for dtype in (jnp.bfloat16, jnp.int8):
        bs = preferred_pool_block(hd, g, dtype)
        launches.extend(audit_launch(
            hd, bs, g=_resolve_block_g(g, hd, dtype), dtype=dtype))
    return launches
