"""Flash attention as a Pallas TPU kernel.

Online-softmax tiling (Dao et al.) mapped to the TPU memory hierarchy:
grid = (batch·heads, q-blocks, k-blocks) executed sequentially with the
k dimension innermost; the f32 accumulator and the running (max, sum)
statistics live in VMEM scratch that persists across the inner k sweep,
so each q tile streams every k/v tile through VMEM exactly once —
O(T·block) VMEM instead of the O(T²) score matrix.  Matmuls hit the MXU
with f32 accumulation (``preferred_element_type``); causal q-blocks that
are entirely above the diagonal are skipped (``@pl.when``), halving the
work for autoregressive models."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.pallas import autodetect_interpret

NEG_INF = -1e30
_LANES = 128          # m/l scratch padded to a full lane tile


def _kernel(q_ref, k_ref, v_ref, o_ref, acc, m, l,
            *, scale, causal, block_q, block_k, nk, tk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, NEG_INF)
        l[:] = jnp.zeros_like(l)

    # causal: skip k blocks entirely above the diagonal
    diag_ok = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(diag_ok)
    def _():
        # dot inputs keep their storage dtype (bf16 rides the MXU at
        # full rate); preferred_element_type pins f32 ACCUMULATION —
        # the standard flash-attention mixed-precision recipe
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = cols < tk                      # key padding
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (rows >= cols)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(m_prev <= NEG_INF / 2, 0.0, corr)
        l[:] = l[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l.shape)
        m[:] = jnp.broadcast_to(m_new, m.shape)
        # probabilities cast DOWN to v's dtype for the MXU; the f32
        # running accumulator preserves precision across k blocks
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        out = acc[:] / jnp.maximum(l[:, :1], 1e-30)
        o_ref[0] = out.astype(o_ref.dtype)


def _pad_to(x, axis, mult):
    t = x.shape[axis]
    pad = (-t) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128, interpret=None):
    """q, k, v: [B, H, T, D] → [B, H, T, D].  ``scale=None`` → 1/√D (same
    default as every entry point in ops.attention).

    Differentiable: the forward pass is the Pallas kernel; the backward
    pass recomputes attention with the pure-jnp online-softmax
    (ops.attention.blockwise_attention) and differentiates through it —
    exact gradients without materializing the T² score matrix.  (A fused
    Pallas backward kernel is a further optimization, not a correctness
    requirement.)"""
    if causal and q.shape[-2] != k.shape[-2]:
        raise ValueError("causal flash kernel assumes tq == tk")
    if not (q.dtype == k.dtype == v.dtype):
        # dot operands keep their storage dtype (MXU-native); mixed
        # inputs must be reconciled by the caller, not silently upcast
        raise ValueError(
            "flash_attention needs matching q/k/v dtypes, got %s/%s/%s"
            % (q.dtype, k.dtype, v.dtype))
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_fn(causal, float(scale), block_q, block_k,
                     autodetect_interpret(interpret))(q, k, v)


@functools.lru_cache(maxsize=None)
def _flash_fn(causal, scale, block_q, block_k, interpret):
    from veles_tpu.ops import attention as att

    @jax.custom_vjp
    def f(q, k, v):
        return _forward(q, k, v, causal, scale, block_q, block_k, interpret)

    def fwd(q, k, v):
        return f(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: att.blockwise_attention(
                q_, k_, v_, causal=causal, scale=scale), q, k, v)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return jax.jit(f)


def _forward(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[-2]
    block_q = min(block_q, max(tq, 8))
    block_k = min(block_k, max(tk, 8))
    qp = _pad_to(q.reshape(b * h, tq, d), 1, block_q)
    kp = _pad_to(k.reshape(b * h, tk, d), 1, block_k)
    vp = _pad_to(v.reshape(b * h, tk, d), 1, block_k)
    nq = qp.shape[1] // block_q
    nk = kp.shape[1] // block_k

    kernel = functools.partial(
        _kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, tk=tk)

    out = pl.pallas_call(
        kernel,
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :tq].reshape(b, h, tq, d)
