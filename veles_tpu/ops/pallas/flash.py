"""Flash attention as a Pallas TPU kernel.

Online-softmax tiling (Dao et al.) mapped to the TPU memory hierarchy:
grid = (batch·heads, q-blocks, k-blocks) executed with the k dimension
innermost; the f32 accumulator and the running (max, sum) statistics
live in VMEM scratch that persists across the inner k sweep, so each q
tile streams every k/v tile through VMEM exactly once — O(T·block) VMEM
instead of the O(T²) score matrix.  Matmuls hit the MXU with f32
accumulation (``preferred_element_type``); causal blocks entirely
off-diagonal are skipped (``@pl.when``), halving the work for
autoregressive models.

The backward pass is fused too (FlashAttention-2): the forward saves
one log-sum-exp residual per q row, and two Pallas kernels produce dQ
(k innermost) and dK/dV (q innermost) from it — no T² matrix in either
direction.  ``backward="recompute"`` keeps the differentiate-through-
blockwise path as a cross-check oracle."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veles_tpu.ops.pallas import autodetect_interpret, register_kernel_audit

NEG_INF = -1e30
_LANES = 128          # m/l scratch padded to a full lane tile


def _masked_scores(x_ref, y_ref, row_start, col_start, scale, causal, tk,
                   rows_are_q, window=None):
    """Scaled score tile xyᵀ with its padding+causal(+sliding-window)
    validity mask — shared by the forward and both backward kernels so
    the three can never desynchronize.  ``rows_are_q``: rows index
    queries and columns keys (forward / dQ); False = the transposed
    dK/dV layout.  Dot inputs keep their storage dtype (bf16 rides the
    MXU at full rate); preferred_element_type pins f32 accumulation."""
    s = jax.lax.dot_general(
        x_ref[0], y_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    rows = row_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = col_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    k_idx, q_idx = (cols, rows) if rows_are_q else (rows, cols)
    valid = k_idx < tk                  # key padding
    if causal:
        valid = valid & (q_idx >= k_idx)
        if window is not None:
            valid = valid & (q_idx - k_idx < window)
    return s, valid


def _block_live(qi, ki, block_q, block_k, causal, window):
    """Whether a (q-block, k-block) tile intersects the causal(+window)
    band at all — dead tiles are skipped entirely (@pl.when)."""
    if not causal:
        return True
    live = ki * block_k <= qi * block_q + block_q - 1
    if window is not None:
        # newest key in the block must still be inside the oldest
        # query's window:  k_max >= q_min - window + 1
        live = live & (ki * block_k + block_k - 1
                       >= qi * block_q - window + 1)
    return live


# --------------------------------------------------------------------------
# Sliding-window grid shrink + causal copy elision.
#
# With a window, each q block's live k blocks form a STATIC-width span
# (window/block geometry), so the inner grid axis only needs that many
# steps instead of all T/block_k — a T=8192/window=1024 forward launches
# ~1/7 of the tiles.  The kernel derives the true k-block index as
# lo(qi) + kj.  Independently, for plain causal masks the dead
# off-diagonal tiles clamp their BlockSpec index to the last live block:
# consecutive grid steps that map to the same block elide the HBM→VMEM
# copy, so skipped tiles stop costing bandwidth too.
# --------------------------------------------------------------------------

def _k_lo(qi, block_q, block_k, window):
    """First live k block for q block ``qi`` under a sliding window."""
    return jnp.maximum(0, (qi * block_q - window + 1) // block_k)


def _k_span(block_q, block_k, window, nk):
    """Static width of the live k-block span per q block."""
    if window is None:
        return nk
    return min(nk, (block_q + window - 2) // block_k + 2)


def _q_lo(ki, block_q, block_k):
    """First live (causal) q block for k block ``ki``."""
    return (ki * block_k) // block_q


def _q_span(block_q, block_k, window, nq):
    """Static width of the live q-block span per k block (window)."""
    if window is None:
        return nq
    return min(nq, (block_k + window - 2) // block_q + 2)


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l,
            *, scale, causal, block_q, block_k, nk, nk_grid, tk, window):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    # with a window the inner axis walks only the live span: the true
    # k-block index is lo(qi) + kj
    ki = (kj if window is None
          else _k_lo(qi, block_q, block_k, window) + kj)

    @pl.when(kj == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, NEG_INF)
        l[:] = jnp.zeros_like(l)

    # skip tiles entirely outside the causal(+window) band (and the
    # shrunken span's overshoot past the last real k block)
    live = _block_live(qi, ki, block_q, block_k, causal, window)
    if window is not None:
        live = live & (ki < nk)

    @pl.when(live)
    def _():
        s, valid = _masked_scores(q_ref, k_ref, qi * block_q,
                                  ki * block_k, scale, causal, tk,
                                  rows_are_q=True, window=window)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(m_prev <= NEG_INF / 2, 0.0, corr)
        l[:] = l[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l.shape)
        m[:] = jnp.broadcast_to(m_new, m.shape)
        # probabilities cast DOWN to v's dtype for the MXU; the f32
        # running accumulator preserves precision across k blocks
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk_grid - 1)
    def _():
        lsum = jnp.maximum(l[:, :1], 1e-30)
        out = acc[:] / lsum
        o_ref[0] = out.astype(o_ref.dtype)
        # log-sum-exp of the scaled scores per q row — the only residual
        # the fused backward needs (p = exp(s - lse) reconstructs
        # exactly).  Stored lane-broadcast (block_q, _LANES): Mosaic
        # requires output block minors (divisible-by-8, 128), which a
        # (1, block_q) row tile violates; the lane copies are sliced
        # off right after the pallas_call.
        lse_ref[0] = m[:] + jnp.log(lsum)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k,
                   nk, nk_grid, tk, window):
    """dQ: grid (bh, q-blocks, k-span), k innermost; dq accumulates in
    f32 VMEM scratch across the k sweep.
        p  = exp(s - lse);  dp = dO·Vᵀ;  ds = p⊙(dp - Δ)·scale
        dq += ds·K
    """
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    ki = (kj if window is None
          else _k_lo(qi, block_q, block_k, window) + kj)

    @pl.when(kj == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = _block_live(qi, ki, block_q, block_k, causal, window)
    if window is not None:
        live = live & (ki < nk)

    @pl.when(live)
    def _():
        s, valid = _masked_scores(q_ref, k_ref, qi * block_q,
                                  ki * block_k, scale, causal, tk,
                                  rows_are_q=True, window=window)
        p = jnp.where(valid, jnp.exp(s - lse_ref[0][:, :1]), 0.0)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        k = k_ref[0]
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk_grid - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, nq, nq_grid, tk, window):
    """dK, dV: grid (bh, k-blocks, q-span), q innermost; both
    accumulators live in f32 VMEM scratch across the q sweep.  The
    score tile keeps the forward orientation (rows = q) so the per-row
    lse/Δ residuals broadcast along lanes without a transpose; the
    k-major products contract over the q rows instead:
        p  = exp(s - lse);          dv += pᵀ·dO
        dp = dO·Vᵀ;  ds = p⊙(dp - Δ)·scale;  dk += dsᵀ·Q
    Padded q rows contribute nothing (their dO and Δ are zero)."""
    ki = pl.program_id(1)
    qj = pl.program_id(2)
    qi = (qj if window is None
          else _q_lo(ki, block_q, block_k) + qj)

    @pl.when(qj == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = _block_live(qi, ki, block_q, block_k, causal, window)
    if window is not None:
        live = live & (qi < nq)

    @pl.when(live)
    def _():
        s, valid = _masked_scores(q_ref, k_ref, qi * block_q,
                                  ki * block_k, scale, causal, tk,
                                  rows_are_q=True,
                                  window=window)              # [bq, bk]
        p = jnp.where(valid, jnp.exp(s - lse_ref[0][:, :1]), 0.0)
        do = do_ref[0]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        q = q_ref[0]
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qj == nq_grid - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _pad_to(x, axis, mult):
    t = x.shape[axis]
    pad = (-t) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _d64_cap(t):
    """Default block cap for the d<=64 VMEM regime: up to 1024, rounded
    to the operand's own padded length (caps follow each operand — in
    non-causal cross-attention tk != tq, and a block_k cap from tq
    would pad K/V up to 8x for nothing)."""
    return max(128, min(1024, -(-t // 128) * 128))


def _resolve_blocks(tq, tk, d, dtype, block_q=None, block_k=None,
                    block_q_dq=None, block_k_dq=None, block_q_dkv=None,
                    block_k_dkv=None):
    """Resolve all six block choices for one flash launch.

    Per knob, first hit wins: explicit argument > site-config key
    (``root.common.engine.flash.*``, with ``*_d64`` variants for head
    dim <= 64) > autotuner winner (``veles_tpu.tuner``, keyed by
    kernel/shape-bucket/dtype/mesh) > built-in default.  The built-in
    forward default is 128 (d>64) or the d64 cap; the backward kernels
    default to the *forward's resolved* geometry — the pre-split
    behavior — so an untuned, unconfigured launch is unchanged.

    d<=64 halves the k/v/q VMEM slabs vs the d=128 the flashtune grid
    swept, so blocks up to 1024 fit — and win: at the 124M flagship's
    (16,12,1024,64) shape, 1024x1024 measured fwd+bwd 16.57 ms vs
    17.44 at the d=128-baked (512,512) and 20.77 XLA-naive; at
    (2,8,8192,64) long context it wins 1.9x (fwd 6.30 vs 11.82 ms) —
    validated across the regime (2026-08-01, .watcher/
    diag_flag_attn.log, diag_d64_long.log)."""
    from veles_tpu.config import root
    fcfg = root.common.engine.flash
    small = d <= 64
    sfx = "_d64" if small else ""

    def cfg(key):
        v = fcfg.get(key + sfx)
        return None if v is None else int(v)

    tuned = {}
    need_tuner = any(b is None and cfg(key) is None for b, key in (
        (block_q, "block_q"), (block_k, "block_k"),
        (block_q_dq, "block_q_dq"), (block_k_dq, "block_k_dq"),
        (block_q_dkv, "block_q_dkv"), (block_k_dkv, "block_k_dkv")))
    if need_tuner:
        try:
            from veles_tpu import tuner
            # fwd/dq grids are q-major (their sweep sizes with tq);
            # the dkv grid walks the KEY axis, so in cross-attention
            # (tq != tk) its winner comes from the tk bucket
            for kern, alias, t in (("flash.fwd", "", tq),
                                   ("flash.bwd_dq", "_dq", tq),
                                   ("flash.bwd_dkv", "_dkv", tk)):
                win = tuner.lookup(kern, tuner.flash_shape_key(t, d),
                                   dtype)
                if win:
                    for wk in ("block_q", "block_k"):
                        if wk in win:
                            tuned[wk + alias] = int(win[wk])
        except Exception:  # noqa: BLE001 — tuning is advisory, never fatal
            pass

    def pick(explicit, key, default):
        if explicit is not None:
            return int(explicit)
        v = cfg(key)
        if v is not None:
            return v
        return int(tuned.get(key, default))

    block_q = pick(block_q, "block_q",
                   _d64_cap(tq) if small else 128)
    block_k = pick(block_k, "block_k",
                   _d64_cap(tk) if small else 128)
    return (block_q, block_k,
            pick(block_q_dq, "block_q_dq", block_q),
            pick(block_k_dq, "block_k_dq", block_k),
            pick(block_q_dkv, "block_q_dkv", block_q),
            pick(block_k_dkv, "block_k_dkv", block_k))


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None, backward="fused",
                    window=None, block_q_dq=None, block_k_dq=None,
                    block_q_dkv=None, block_k_dkv=None):
    """q, k, v: [B, H, T, D] → [B, H, T, D].  ``scale=None`` → 1/√D (same
    default as every entry point in ops.attention).

    Block sizes resolve per kernel — forward (``block_q``/``block_k``),
    dQ (``block_q_dq``/``block_k_dq``) and dK/dV (``block_q_dkv``/
    ``block_k_dkv``) grids are independent: explicit argument > site
    config (``root.common.engine.flash.*``, ``*_d64`` keys for head
    dim <= 64) > autotuner winner (``veles_tpu.tuner``; populate with
    ``veles-tpu-tune sweep`` or ``bench.py --phase flashtune``) >
    built-in default (see :func:`_resolve_blocks`).  Unset backward
    blocks inherit the forward's resolved geometry.

    Differentiable both ways: ``backward="fused"`` (default) runs the
    Pallas dQ and dK/dV kernels against the forward's saved log-sum-exp
    residual (the FlashAttention-2 recipe — no T² matrix, two extra
    passes over K/V); ``backward="recompute"`` differentiates through the
    pure-jnp online-softmax (ops.attention.blockwise_attention) instead —
    slower, kept as the cross-check oracle for the kernel tests."""
    if causal and q.shape[-2] != k.shape[-2]:
        raise ValueError("causal flash kernel assumes tq == tk")
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError("window must be >= 1")
        window = int(window)
    if not (q.dtype == k.dtype == v.dtype):
        # dot operands keep their storage dtype (MXU-native); mixed
        # inputs must be reconciled by the caller, not silently upcast
        raise ValueError(
            "flash_attention needs matching q/k/v dtypes, got %s/%s/%s"
            % (q.dtype, k.dtype, v.dtype))
    if backward not in ("fused", "recompute"):
        raise ValueError("backward must be 'fused' or 'recompute'")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    blocks = _resolve_blocks(
        q.shape[-2], k.shape[-2], q.shape[-1], q.dtype,
        block_q=block_q, block_k=block_k,
        block_q_dq=block_q_dq, block_k_dq=block_k_dq,
        block_q_dkv=block_q_dkv, block_k_dkv=block_k_dkv)
    return _flash_fn(causal, float(scale), blocks,
                     autodetect_interpret(interpret), backward,
                     window)(q, k, v)


@functools.lru_cache(maxsize=None)
def _flash_fn(causal, scale, blocks, interpret, backward,
              window=None):
    from veles_tpu.ops import attention as att
    block_q, block_k = blocks[:2]
    bwd_blocks = blocks[2:]

    @jax.custom_vjp
    def f(q, k, v):
        out, _ = _forward(q, k, v, causal, scale, block_q, block_k,
                          interpret, window)
        return out

    def fwd(q, k, v):
        out, lse = _forward(q, k, v, causal, scale, block_q, block_k,
                            interpret, window)
        # the recompute oracle only re-derives from q/k/v — saving
        # (out, lse) there would hold an extra [B,H,T,D] + [B,H,T]
        # activation per attention call for nothing
        res = (q, k, v, out, lse) if backward == "fused" else (q, k, v)
        return out, res

    def bwd(res, g):
        if backward == "fused":
            q, k, v, out, lse = res
            return _backward(q, k, v, out, lse, g, causal, scale,
                             bwd_blocks, interpret, window)
        q, k, v = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: att.blockwise_attention(
                q_, k_, v_, causal=causal, scale=scale,
                window=window), q, k, v)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return jax.jit(f)


def _blocks(q, k, v, block_q, block_k):
    b, h, tq, d = q.shape
    tk = k.shape[-2]
    block_q = min(block_q, max(tq, 8))
    block_k = min(block_k, max(tk, 8))
    qp = _pad_to(q.reshape(b * h, tq, d), 1, block_q)
    kp = _pad_to(k.reshape(b * h, tk, d), 1, block_k)
    vp = _pad_to(v.reshape(b * h, tk, d), 1, block_k)
    return (qp, kp, vp, block_q, block_k,
            qp.shape[1] // block_q, kp.shape[1] // block_k)


#: bh and q/k-blocks carry no cross-iteration state (scratch resets at
#: inner index 0) — declaring them parallel lets Mosaic re-order /
#: parallelize them; only the innermost sweep is a sequential reduction
#: (CompilerParams is the current name; 0.4.x spells it
#: TPUCompilerParams)
_SEMANTICS = getattr(pltpu, "CompilerParams",
                     getattr(pltpu, "TPUCompilerParams", None))(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


def _kv_index_map(block_q, block_k, causal, window, nk):
    """BlockSpec index map for k/v on a (bh, q-block, k-inner) grid:
    resolves the shrunken window span to true k blocks, and clamps dead
    causal tiles onto the diagonal block so the pipeline elides their
    HBM→VMEM copies (same index on consecutive steps = no copy)."""
    def index_map(bh, qi, kj):
        ki = (kj if window is None
              else _k_lo(qi, block_q, block_k, window) + kj)
        if causal:
            hi = (qi * block_q + block_q - 1) // block_k
            ki = jnp.minimum(ki, jnp.minimum(hi, nk - 1))
        return (bh, ki, 0)
    return index_map


def _forward(q, k, v, causal, scale, block_q, block_k, interpret,
             window=None):
    b, h, tq, d = q.shape
    tk = k.shape[-2]
    qp, kp, vp, block_q, block_k, nq, nk = _blocks(q, k, v, block_q,
                                                   block_k)
    nk_grid = _k_span(block_q, block_k, window, nk) if causal else nk

    kernel = functools.partial(
        _kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, nk_grid=nk_grid,
        tk=tk, window=window)

    kv_map = _kv_index_map(block_q, block_k, causal, window, nk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, nq, nk_grid),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qp.shape, q.dtype),
            jax.ShapeDtypeStruct(qp.shape[:2] + (_LANES,), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(qp, kp, vp)
    # residual kept lean: drop the lane copies (the backward re-broadcasts)
    return out[:, :tq].reshape(b, h, tq, d), lse[:, :, 0]


def _backward(q, k, v, out, lse, g, causal, scale, blocks, interpret,
              window=None):
    """FlashAttention-2 backward: Δ = rowsum(dO⊙O) in plain XLA (one
    fused elementwise+reduce), then the dQ kernel (k innermost) and the
    dK/dV kernel (q innermost).  ``blocks`` carries each kernel's OWN
    (block_q, block_k) pair — the dq and dkv grids are independent of
    the forward's geometry and of each other (dq streams K/V per q
    tile, dkv streams Q/dO per k tile; their optimal tile trade-offs
    differ, see veles_tpu/tuner).  Each launch pads its operands to its
    own block multiple.  Gradients come back in the inputs' dtype; all
    accumulation is f32."""
    bq_dq, bk_dq, bq_dkv, bk_dkv = blocks
    b, h, tq, d = q.shape
    tk = k.shape[-2]
    # residuals at full resolution, padded per launch below.  Per-row
    # residuals enter the kernels lane-broadcast — Mosaic wants
    # (sublane % 8, lane % 128) block minors, which (1, block_q) row
    # tiles violate; one fused XLA broadcast each, tiny next to the
    # kernels' K/V traffic
    do = g.reshape(b * h, tq, d).astype(q.dtype)
    delta = jnp.sum(do.astype(jnp.float32)
                    * out.reshape(b * h, tq, d).astype(jnp.float32),
                    axis=-1)
    lse = jnp.broadcast_to(lse[:, :, None], lse.shape + (_LANES,))
    delta = jnp.broadcast_to(delta[:, :, None], delta.shape + (_LANES,))

    # ---------------------------------------------------- dQ launch
    qp, kp, vp, block_q, block_k, nq, nk = _blocks(q, k, v, bq_dq,
                                                   bk_dq)
    dop = _pad_to(do, 1, block_q)
    lse_p = _pad_to(lse, 1, block_q)
    delta_p = _pad_to(delta, 1, block_q)
    nk_grid = _k_span(block_q, block_k, window, nk) if causal else nk
    kv_map = _kv_index_map(block_q, block_k, causal, window, nk)
    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, a, i: (bh, a, 0))
    r_spec = pl.BlockSpec((1, block_q, _LANES),
                          lambda bh, a, i: (bh, a, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), kv_map)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          nk_grid=nk_grid, tk=tk, window=window),
        grid=(b * h, nq, nk_grid),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, r_spec, r_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    # --------------------------------------------------- dK/dV launch
    # q innermost: swap the roles of the two block axes in the specs;
    # the q/do/residual index map mirrors _kv_index_map (window span
    # shrink + clamp of the dead below-diagonal tiles onto the first
    # live q block for copy elision)
    qp, kp, vp, block_q, block_k, nq, nk = _blocks(q, k, v, bq_dkv,
                                                   bk_dkv)
    dop = _pad_to(do, 1, block_q)
    lse_p = _pad_to(lse, 1, block_q)
    delta_p = _pad_to(delta, 1, block_q)
    nq_grid = _q_span(block_q, block_k, window, nq) if causal else nq

    def q_map3(bh, ki, qj):
        qi = (qj if window is None
              else _q_lo(ki, block_q, block_k) + qj)
        if causal:
            lo = _q_lo(ki, block_q, block_k)
            qi = jnp.minimum(jnp.maximum(qi, lo), nq - 1)
        return (bh, qi, 0)

    q_spec2 = pl.BlockSpec((1, block_q, d), q_map3)
    r_spec2 = pl.BlockSpec((1, block_q, _LANES), q_map3)
    kv_spec2 = pl.BlockSpec((1, block_k, d), lambda bh, a, i: (bh, a, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq,
                          nq_grid=nq_grid, tk=tk, window=window),
        grid=(b * h, nk, nq_grid),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct(kp.shape, k.dtype),
                   jax.ShapeDtypeStruct(vp.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta_p)

    return (dq[:, :tq].reshape(b, h, tq, d),
            dk[:, :tk].reshape(b, h, tk, d),
            dv[:, :tk].reshape(b, h, tk, d))


# --------------------------------------------------------------------------
# VP6xx launch-audit hook (analysis.numerics_audit): the SAME geometry
# the pallas_calls above launch — block tiles per in/out spec, VMEM
# scratch per scratch_shapes, grid divisibility — described as data.
# Pure arithmetic: nothing is traced, compiled, or dispatched.
# --------------------------------------------------------------------------

def audit_launch(tq, tk, d, dtype=jnp.bfloat16, causal=False,
                 block_q=None, block_k=None, window=None, masked=True,
                 checked=(), block_q_dq=None, block_k_dq=None,
                 block_q_dkv=None, block_k_dkv=None, kernels=None):
    """Launch descriptions for one flash configuration — forward, dQ
    and dK/dV kernels, each at its OWN (block_q, block_k) geometry
    (unset backward blocks inherit the forward's, the same rule
    ``_resolve_blocks`` applies).  ``kernels`` optionally restricts the
    output to a subset of ``{"forward", "bwd_dq", "bwd_dkv"}`` — the
    autotuner audits one candidate kernel at a time.  ``masked=True``
    reflects what the kernels actually do (``_pad_to`` + validity mask
    — the VP601 escape hatch); the tests pin a ``masked=False``
    description to prove VP601 fires when a kernel does not."""
    if block_q is None:
        block_q = 128
    if block_k is None:
        block_k = 128
    # the lane dim of every head-dim tile IS the model's head dim —
    # geometry, not a tunable block choice (full_lane exempts it from
    # VP600; d=64 models are real and the kernel handles the half-tile)
    hd = {"full_lane": True}

    def geom(bq, bk):
        bq = min(int(bq), max(tq, 8))
        bk = min(int(bk), max(tk, 8))
        qkv = [("q", (1, bq, d), dtype, hd),
               ("k", (1, bk, d), dtype, hd),
               ("v", (1, bk, d), dtype, hd)]
        grid = [("q-blocks", tq, bq), ("k-blocks", tk, bk)]
        resid = [("do", (1, bq, d), dtype, hd),
                 ("lse", (1, bq, _LANES), jnp.float32),
                 ("delta", (1, bq, _LANES), jnp.float32)]
        return bq, bk, qkv, grid, resid

    launches = []
    if kernels is None or "forward" in kernels:
        bq, bk, qkv, grid, _ = geom(block_q, block_k)
        launches.append({
            "kernel": "flash.forward", "masked": masked,
            "checked": checked,
            "blocks": qkv + [("o", (1, bq, d), dtype, hd),
                             ("lse", (1, bq, _LANES), jnp.float32)],
            "scratch": [("acc", (bq, d), jnp.float32),
                        ("m", (bq, _LANES), jnp.float32),
                        ("l", (bq, _LANES), jnp.float32)],
            "grid_axes": grid,
        })
    if kernels is None or "bwd_dq" in kernels:
        bq, bk, qkv, grid, resid = geom(
            block_q if block_q_dq is None else block_q_dq,
            block_k if block_k_dq is None else block_k_dq)
        launches.append({
            "kernel": "flash.bwd_dq", "masked": masked,
            "checked": checked,
            "blocks": qkv + resid + [("dq", (1, bq, d), dtype, hd)],
            "scratch": [("dq_acc", (bq, d), jnp.float32)],
            "grid_axes": grid,
        })
    if kernels is None or "bwd_dkv" in kernels:
        bq, bk, qkv, grid, resid = geom(
            block_q if block_q_dkv is None else block_q_dkv,
            block_k if block_k_dkv is None else block_k_dkv)
        launches.append({
            "kernel": "flash.bwd_dkv", "masked": masked,
            "checked": checked,
            "blocks": qkv + resid + [("dk", (1, bk, d), dtype, hd),
                                     ("dv", (1, bk, d), dtype, hd)],
            "scratch": [("dk_acc", (bk, d), jnp.float32),
                        ("dv_acc", (bk, d), jnp.float32)],
            "grid_axes": grid,
        })
    return launches


@register_kernel_audit("flash")
def _configured_launches():
    """The block sizes ``flash_attention`` would actually pick — the
    full resolution chain (site config > tuner winners > defaults,
    exactly ``_resolve_blocks``), audited at both head-dim regimes (the
    d=128 flashtune keys and the d<=64 ``*_d64`` keys) in the
    MXU-native bf16.  A tuned-but-over-budget winner therefore fails
    ``veles-tpu-lint --numerics`` the same way a hand-misconfigured
    site key always has."""
    launches = []
    t = 1024
    for d in (128, 64):
        bq, bk, bq_dq, bk_dq, bq_dkv, bk_dkv = _resolve_blocks(
            t, t, d, jnp.bfloat16)
        launches += audit_launch(
            t, t, d, causal=True, block_q=bq, block_k=bk,
            block_q_dq=bq_dq, block_k_dq=bk_dq,
            block_q_dkv=bq_dkv, block_k_dkv=bk_dkv)
    return launches
