"""Pallas TPU kernels for the hot ops (see /opt/skills/guides/pallas_guide.md).

Kernels here are the hand-tiled VMEM path; every one has an XLA or numpy
equivalent elsewhere in ops/ that serves as ground truth in the tests.
Off-TPU the kernels run in interpret mode (``interpret=None`` auto-detects),
so the same code is exercised by the CPU test suite.

Every kernel module also registers a **launch-audit hook**
(:func:`register_kernel_audit`): a pure function that reports the
kernel's launch geometry — VMEM block shapes, scratch allocations, grid
divisibility, masking — for its *configured* block sizes, without
building or compiling anything.  ``analysis.numerics_audit`` runs the
VP6xx rules (tile alignment, ragged-grid masking, VMEM footprint) over
these descriptions, so a mis-sized ``root.common.engine.flash.block_q``
or an over-budget tile is caught by ``veles-tpu-lint --numerics``
before any chip sees the kernel (docs/static_analysis.md)."""

import jax


def autodetect_interpret(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def mosaic_sublane_min(dtype):
    """Mosaic's minimum second-to-last-dim tile for ``dtype`` on TPU:
    8 rows for 4-byte types, 16 for bf16/f16, 32 for int8/fp8 (pallas
    guide, 'Block shape alignment').  THE one copy of the table: the
    paged-serving fused-tick fallback (models.generate) and the VP600
    tile lint (analysis.numerics_audit) must agree on which blocks
    compile."""
    import numpy as np
    return {4: 8, 2: 16, 1: 32}.get(np.dtype(dtype).itemsize, 8)


#: kernel name -> callable() -> [launch dict] (the shape
#: ``analysis.numerics_audit.audit_kernel_launch`` consumes)
KERNEL_AUDITS = {}


def register_kernel_audit(name):
    """Decorator: register a zero-arg launch-description hook for the
    VP6xx Pallas audit.  The hook must be pure geometry — no tracing,
    no compilation, no device access."""
    def deco(fn):
        KERNEL_AUDITS[name] = fn
        return fn
    return deco


def kernel_audit_launches():
    """All registered kernels' launch descriptions at their configured
    geometry.  Importing the kernel modules here (not at package
    import) keeps the base package light — the audit is the only
    consumer."""
    from veles_tpu.ops.pallas import flash, paged  # noqa: F401 — register
    launches = []
    for name in sorted(KERNEL_AUDITS):
        launches.extend(KERNEL_AUDITS[name]())
    return launches
