"""Pallas TPU kernels for the hot ops (see /opt/skills/guides/pallas_guide.md).

Kernels here are the hand-tiled VMEM path; every one has an XLA or numpy
equivalent elsewhere in ops/ that serves as ground truth in the tests.
Off-TPU the kernels run in interpret mode (``interpret=None`` auto-detects),
so the same code is exercised by the CPU test suite."""

import jax


def autodetect_interpret(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret
