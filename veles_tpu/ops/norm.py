"""Normalization ops: layer norm, RMS norm, batch norm.

The reference only ships LRN ("norm" layer, ocl/cuda kernels absent with
Znicz); layer/RMS norm are required by the transformer stack
(ops.attention) and are new capability beyond parity.  All reductions in
f32 regardless of the compute dtype — matches the framework-wide policy of
bf16 storage + f32 accumulation (ops.policy)."""

import jax.numpy as jnp


def layer_norm(x, gamma=None, beta=None, eps=1e-6, axis=-1):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.var(xf, axis=axis, keepdims=True)
    y = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm(x, gamma=None, eps=1e-6, axis=-1):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=axis, keepdims=True)
    y = xf * jnp.reciprocal(jnp.sqrt(ms + eps))
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    return y.astype(x.dtype)


def layer_norm_init(shape):
    return {"gamma": jnp.ones(shape, jnp.float32),
            "beta": jnp.zeros(shape, jnp.float32)}


def batch_norm(x, mean, var, gamma, beta, eps=1e-5):
    """Inference-mode batch norm with running statistics."""
    xf = x.astype(jnp.float32)
    y = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps)) * gamma + beta
    return y.astype(x.dtype)
