"""Normalization ops: layer norm, RMS norm, batch norm.

The reference only ships LRN ("norm" layer, ocl/cuda kernels absent with
Znicz); layer/RMS norm are required by the transformer stack
(ops.attention) and are new capability beyond parity.  All reductions in
f32 regardless of the compute dtype — matches the framework-wide policy of
bf16 storage + f32 accumulation (ops.policy)."""

import jax.numpy as jnp


def layer_norm(x, gamma=None, beta=None, eps=1e-6, axis=-1):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.var(xf, axis=axis, keepdims=True)
    y = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm(x, gamma=None, eps=1e-6, axis=-1):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=axis, keepdims=True)
    y = xf * jnp.reciprocal(jnp.sqrt(ms + eps))
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    return y.astype(x.dtype)


def layer_norm_init(shape):
    return {"gamma": jnp.ones(shape, jnp.float32),
            "beta": jnp.zeros(shape, jnp.float32)}


def group_norm(x, gamma=None, beta=None, groups=32, eps=1e-5):
    """Group normalization over BATCHED input ``[B, ..., C]`` (axis 0 is
    always the batch; pass ``x[None]`` for a single sample): channels
    split into groups; mean/var reduce per (sample, group) over the
    spatial dims and intra-group channels.  Batch-size independent
    (identical train/eval behavior) — the modern conv-net normalizer
    that needs no running statistics, so it slots into the stateless
    functional layer contract where batch norm's mutable running
    mean/var cannot.

    The effective group count is the largest divisor of C that is
    <= ``groups`` (channels must split evenly; e.g. C=48, groups=32
    → 24 groups)."""
    if x.ndim < 2:
        raise ValueError(
            "group_norm expects batched input [B, ..., C]; got rank %d"
            % x.ndim)
    c = x.shape[-1]
    g = max(1, min(int(groups), c))
    while c % g:        # largest divisor of C that is <= groups
        g -= 1
    xf = x.astype(jnp.float32)
    xg = xf.reshape(x.shape[:-1] + (g, c // g))
    # axis 0 is the batch; per sample, reduce EVERY dim (spatial + the
    # intra-group channels) except the group axis itself
    red = tuple(i for i in range(1, xg.ndim) if i != xg.ndim - 2)
    mean = jnp.mean(xg, axis=red, keepdims=True)
    var = jnp.var(xg, axis=red, keepdims=True)
    y = ((xg - mean) * jnp.reciprocal(jnp.sqrt(var + eps))).reshape(
        x.shape)
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    return y.astype(x.dtype)


def batch_norm(x, mean, var, gamma, beta, eps=1e-5):
    """Inference-mode batch norm with running statistics."""
    xf = x.astype(jnp.float32)
    y = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps)) * gamma + beta
    return y.astype(x.dtype)
