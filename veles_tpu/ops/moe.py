"""Mixture-of-experts feed-forward with expert parallelism.

New capability beyond the reference (SURVEY.md §2.6: EP absent there),
mandated first-class for the TPU build.  The design is the canonical
TPU MoE (GShard / Switch): routing produces a *dense* dispatch tensor
[tokens, experts, capacity] so every shape is static and the dispatch/
combine contractions run on the MXU — no sorting, no dynamic shapes.

Two execution paths share the math:

* single-device: the dispatch einsum materializes [E, C, D] expert
  batches locally.
* expert-parallel (inside ``shard_map`` over an ``expert`` axis):
  tokens are sharded over the axis; after local dispatch,
  ``lax.all_to_all`` swaps the expert dim for the shard dim so each
  device runs only its local experts, then the inverse all-to-all
  brings expert outputs home for the combine.  The two all-to-alls ride
  ICI — the standard GShard dance.
"""

import math

import jax
import jax.numpy as jnp
from jax import lax


def moe_init(rng, d_model, d_ff, n_experts, dtype=jnp.float32):
    """Router + per-expert MLP params (experts stacked on axis 0)."""
    std = 1.0 / math.sqrt(d_model)
    return {
        "router": jnp.asarray(rng.normal(0.0, std, (d_model, n_experts)),
                              dtype),
        "w1": jnp.asarray(rng.normal(0.0, std, (n_experts, d_model, d_ff)),
                          dtype),
        "b1": jnp.zeros((n_experts, d_ff), dtype),
        "w2": jnp.asarray(
            rng.normal(0.0, 1.0 / math.sqrt(d_ff), (n_experts, d_ff,
                                                    d_model)), dtype),
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def _routing(x2d, router, n_experts, capacity, top_k):
    """Dense dispatch/combine tensors (GShard §3.2, Switch §2.2).

    Returns (dispatch [N, E, C] one-hot, combine [N, E, C] weighted) plus
    the load-balancing auxiliary loss (Switch eq. 4)."""
    logits = x2d.astype(jnp.float32) @ router.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)          # [N, E]

    gates = jnp.zeros_like(probs)
    masked = probs
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)
        onehot = jax.nn.one_hot(idx, n_experts, dtype=probs.dtype)
        gates = gates + onehot * probs
        masked = masked * (1.0 - onehot)
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)

    # position of each token within its expert's capacity buffer
    chosen = gates > 0.0                             # [N, E]
    position = (jnp.cumsum(chosen, axis=0) - 1.0) * chosen
    fits = chosen & (position < capacity)
    pos_onehot = jax.nn.one_hot(position.astype(jnp.int32), capacity,
                                dtype=probs.dtype)   # [N, E, C]
    dispatch = pos_onehot * fits[..., None]
    combine = dispatch * gates[..., None]

    # Switch load-balancing aux loss: E * Σ_e fraction_tokens_e · mean_prob_e
    frac = chosen.astype(jnp.float32).mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = n_experts * jnp.sum(frac * mean_prob) / top_k
    return dispatch, combine, aux


def _expert_mlp(w1, b1, w2, b2, h):
    """h: [E(, ...), C, D] with matching leading expert dims on w/b."""
    h = jnp.einsum("...cd,...df->...cf", h, w1) + b1[..., None, :]
    h = jax.nn.gelu(h)
    return jnp.einsum("...cf,...fd->...cd", h, w2) + b2[..., None, :]


def moe_forward(params, x, top_k=2, capacity_factor=2.0, axis_name=None,
                policy=None):
    """x: [B, T, D] → ([B, T, D], aux_loss).

    ``axis_name``: inside shard_map, run expert-parallel over that mesh
    axis (n_experts must be divisible by the axis size; tokens arrive
    sharded over the same axis via the batch dim)."""
    b, t, d = x.shape
    n_experts = params["router"].shape[-1]
    x2d = x.reshape(b * t, d)
    n = b * t
    try:
        capacity = max(1, int(capacity_factor * n * top_k / n_experts))
    except (TypeError, jax.errors.ConcretizationTypeError):
        # jax.export symbolic batch: ``n`` is a dimension expression —
        # float math on it concretizes.  Keep the capacity a dim expr
        # via integer arithmetic (capacity_factor rationalized /1000)
        # so one artifact still serves any batch size.
        import jax.core as jcore
        num = int(round(capacity_factor * 1000))
        capacity = jcore.max_dim(
            (n * top_k * num) // (1000 * n_experts), 1)
    cast = (lambda a: a) if policy is None else policy.cast_in

    dispatch, combine, aux = _routing(x2d, params["router"], n_experts,
                                      capacity, top_k)
    # [N, E, C] x [N, D] -> [E, C, D] expert input batches
    expert_in = jnp.einsum("nec,nd->ecd", cast(dispatch), cast(x2d),
                           preferred_element_type=jnp.float32)

    if axis_name is None:
        expert_out = _expert_mlp(params["w1"], params["b1"], params["w2"],
                                 params["b2"], expert_in)
    else:
        shards = lax.psum(1, axis_name)
        e_local = n_experts // shards
        w1, b1, w2, b2 = (params["w1"], params["b1"], params["w2"],
                          params["b2"])
        if w1.shape[0] == n_experts:   # replicated params: take my slice
            me = lax.axis_index(axis_name)
            w1 = lax.dynamic_slice_in_dim(w1, me * e_local, e_local)
            b1 = lax.dynamic_slice_in_dim(b1, me * e_local, e_local)
            w2 = lax.dynamic_slice_in_dim(w2, me * e_local, e_local)
            b2 = lax.dynamic_slice_in_dim(b2, me * e_local, e_local)
        # device-transpose: [S_owner, e_local, C, D] of MY tokens becomes
        # [S_source, e_local, C, D] of MY experts (axis0 slice i goes to
        # device i; received slices stack back on axis0 keyed by sender)
        grouped = expert_in.reshape(shards, e_local, capacity, d)
        recv = lax.all_to_all(grouped, axis_name, 0, 0)
        h = recv.transpose(1, 0, 2, 3).reshape(e_local, shards * capacity,
                                               d)
        out = _expert_mlp(w1, b1, w2, b2, h)
        out = out.reshape(e_local, shards, capacity, d).transpose(1, 0, 2, 3)
        expert_out = lax.all_to_all(out, axis_name, 0, 0).reshape(
            n_experts, capacity, d)

    y = jnp.einsum("ecd,nec->nd", expert_out.astype(jnp.float32),
                   combine.astype(jnp.float32))
    return y.reshape(b, t, d).astype(x.dtype), aux


def moe_forward_sharded(params, x, mesh, expert_axis="expert", top_k=2,
                        capacity_factor=2.0, policy=None):
    """Global [B, T, D] arrays → expert-parallel MoE over ``expert_axis``.

    Expert weights shard over the axis (each device computes only its
    experts), the batch shards over the same axis (tokens all_to_all to
    their experts and back), router/aux replicate.  Composes with
    jit/grad like every shard_map here."""
    from jax.sharding import PartitionSpec as P

    from veles_tpu.parallel.smap import shard_map

    axis_size = mesh.shape[expert_axis]
    n_experts = params["w1"].shape[0]
    if x.shape[0] % axis_size:
        raise ValueError("batch %d not divisible by %s axis size %d"
                         % (x.shape[0], expert_axis, axis_size))
    if n_experts % axis_size:
        raise ValueError("n_experts %d not divisible by %s axis size %d"
                         % (n_experts, expert_axis, axis_size))
    e = P(expert_axis)
    param_specs = {"router": P(), "w1": e, "b1": e, "w2": e, "b2": e}
    xspec = P(expert_axis)          # batch dim sharded over the axis

    def fn(p, xs):
        y, aux = moe_forward(p, xs, top_k=top_k,
                             capacity_factor=capacity_factor,
                             axis_name=expert_axis, policy=policy)
        return y, lax.pmean(aux, expert_axis)

    return shard_map(fn, mesh=mesh, in_specs=(param_specs, xspec),
                     out_specs=(xspec, P()), check_vma=False)(params, x)
