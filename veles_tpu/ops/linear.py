"""Dense (All2All) ops — the TPU equivalent of the reference's tiled matmul
kernel family (`ocl/matrix_multiplication*.cl`, `ocl/gemm.cl`) and the Znicz
All2All forward/backward units (SURVEY.md §2.9 "Dense").

The reference hand-tiled gemm with autotuned BLOCK_SIZE per device
(`veles/backends.py:672-731`); XLA owns that job on TPU — these are plain
``jnp.dot`` calls shaped for the MXU with float32 accumulation."""

import jax.numpy as jnp

from veles_tpu.ops.policy import Policy


def matmul(a, b, policy=Policy()):
    """MXU matmul with compute-dtype inputs and accum-dtype output — the
    gemm primitive (ref ocl/gemm.cl signature αAB+βC collapses to XLA).
    A quantized serving weight (ops.quant QuantWeight/QuantWeight4)
    routes to the scheme's quantized dot instead — the payload reaches
    the dot itself, never a dequantized copy."""
    from veles_tpu.ops.quant import is_quant, quant_matmul
    if is_quant(b):
        return quant_matmul(a, b)
    return jnp.dot(policy.cast_in(a), policy.cast_in(b),
                   preferred_element_type=policy.accum)


def forward(params, x, policy=Policy()):
    """All2All forward: y = x W + b (ref Znicz all2all; weights stored
    (in, out) so the batch dim rides the MXU rows).

    LoRA (Hu et al. 2021): when the param tree carries ``lora_a``
    [in, r] / ``lora_b`` [r, out] adapters, the effective weight is
    W + A·B with the BASE W and b frozen via stop_gradient — training
    touches only the rank-r factors (alpha = r convention, scale 1).
    ``lora_b`` initializes to zero, so a freshly adapted layer computes
    exactly the base layer."""
    import jax
    xf = x.reshape(x.shape[0], -1)
    if "lora_a" in params:
        y = matmul(xf, jax.lax.stop_gradient(params["weights"]), policy)
        y = y + matmul(matmul(xf, params["lora_a"], policy),
                       params["lora_b"], policy)
        if "bias" in params:
            y = y + jax.lax.stop_gradient(
                params["bias"]).astype(policy.accum)
        return y
    y = matmul(xf, params["weights"], policy)
    if "bias" in params:
        y = y + params["bias"].astype(policy.accum)
    return y


def init_params(rng, n_in, n_out, bias=True, weights_stddev=None,
                dtype=jnp.float32):
    """Weight filler matching the reference's default scheme: uniform in
    [-s, s] with s = 1/sqrt(n_in) unless overridden (Znicz
    ``weights_stddev`` parameter)."""
    s = weights_stddev if weights_stddev is not None else n_in ** -0.5
    params = {"weights": rng.fill_uniform((n_in, n_out), s).astype(dtype)}
    if bias:
        params["bias"] = jnp.zeros((n_out,), dtype)
    return params
