"""Topology helper ops (ref Znicz Cutter, ChannelSplitter/ChannelMerger,
ZeroFiller — SURVEY.md §2.9 "Regularization/topology")."""

import jax.numpy as jnp


def cut(x, offset_y, offset_x, height, width):
    """Cutter: static crop of an NHWC tensor (GDCutter is jax.grad's job)."""
    return x[:, offset_y:offset_y + height, offset_x:offset_x + width, :]


def channel_split(x):
    """ChannelSplitter: NHWC -> list of per-channel NHW1 tensors."""
    return [x[..., c:c + 1] for c in range(x.shape[-1])]


def channel_merge(channels):
    """ChannelMerger: inverse of channel_split."""
    return jnp.concatenate(channels, axis=-1)


def zero_fill(weights, mask):
    """ZeroFiller: force a fixed sparsity pattern on a weight tensor; applied
    after every update so masked weights stay exactly zero."""
    return weights * mask


def input_join(*tensors):
    """InputJoiner: concat along the feature axis (ref veles/input_joiner.py
    + ocl/join.jcl — on TPU a plain concatenate XLA fuses)."""
    flat = [t.reshape(t.shape[0], -1) for t in tensors]
    return jnp.concatenate(flat, axis=1)
