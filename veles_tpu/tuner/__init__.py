"""veles_tpu.tuner — persistent on-device kernel autotuner.

BENCH_r05's headline gap motivated this subsystem: the hand-picked
Pallas flash *backward* ran 6.95 ms where plain XLA managed 3.99 — the
custom kernel was 1.7x slower than the compiler because its dq/dkv
grids were yoked to the forward's block choice.  The fix is the
TVM / CLBlast move (PAPERS.md): don't hand-pick block sizes, *search*
the config space per (shape, dtype, mesh) and persist the winners.

The pieces:

* :class:`KernelTuner` — measures candidate configs on-device
  (median-of-k, warm-up discarded), validates every candidate through
  the VP6xx tile/VMEM launch audit (``analysis.numerics_audit``)
  *before* it may win, and persists winners in a JSON cache
  (:mod:`veles_tpu.tuner.cache`) keyed by
  ``kernel|shape-bucket|dtype|mesh`` next to ``compile_cache``'s
  directory;
* :mod:`veles_tpu.tuner.sweeps` — candidate enumeration + chained
  measurement harnesses for the flash forward, the split dq/dkv
  backward kernels, and the fused paged decode kernel;
* :mod:`veles_tpu.tuner.cli` — ``veles-tpu-tune`` (also reachable as
  ``python -m veles_tpu --tune``): sweep/list/clear the winner cache.

Launch-time contract: kernels call :func:`lookup` when neither an
explicit argument nor a site-config key pinned their blocks — config
override always wins over a tuned winner (ops/pallas/flash.py
``_resolve_blocks``, ops/pallas/paged.py).  With no accelerator and no
cache entry the lookup misses and the kernel falls back to its current
defaults: tuning never happens implicitly, only ``veles-tpu-tune
sweep`` / ``bench.py --phase flashtune`` measure.

Mesh elasticity: winners are keyed by mesh topology, and
``on_mesh_refit`` (called from the launcher's elastic-mesh path, PR 10)
*invalidates* the configured-topology entries so a degraded pod
re-tunes for its survivor mesh instead of inheriting full-size
configs.

Telemetry: every lookup records a ``tune.hit``/``tune.miss`` flight
event and bumps ``veles_tune_lookups_total``; sweeps record
``tune.sweep`` and ``veles_tune_sweeps_total``; the winner count is
the ``veles_tune_winners`` gauge.  See docs/perf.md "Autotuning".
"""

import os
import statistics
import threading

from veles_tpu.tuner.cache import WinnerCache

__all__ = [
    "KernelTuner", "SweepResult", "default_cache_path", "flash_shape_key",
    "get_tuner", "lookup", "mesh_descriptor", "on_mesh_refit",
    "paged_shape_key", "reset", "set_ambient_mesh",
]


# --------------------------------------------------------------------------
# Cache location + keying
# --------------------------------------------------------------------------

def default_cache_path():
    """``<repo>/.veles_tune/winners.json`` — a sibling of
    ``compile_cache``'s ``.xla_cache`` directory (same repo-local
    scratch economics: survives process restarts within a TPU window,
    visible to the driver's end-of-round bench).  Overridden by
    ``VELES_TUNE_CACHE`` (a path; ``0/off/false/no`` disables
    persistence entirely — the memory-only mode)."""
    env = os.environ.get("VELES_TUNE_CACHE", "")
    if env.lower() in ("0", "off", "false", "no"):
        return None
    if env and env.lower() not in ("1", "on", "true", "yes"):
        return os.path.abspath(env)
    from veles_tpu import compile_cache
    return os.path.join(os.path.dirname(compile_cache.default_dir()),
                        ".veles_tune", "winners.json")


def _bucket(n, floor=128):
    """Shape-bucket a sequence length: next power of two >= n (floored)
    — a T=1000 launch shares the T=1024 winner instead of missing the
    cache for every ragged sequence length."""
    n = max(int(n), 1)
    b = floor
    while b < n:
        b *= 2
    return b


def flash_shape_key(t, d):
    """Bucketed shape key for the flash kernels: sequence length to the
    next power of two, head dim exact (it IS the lane geometry)."""
    return "t%d_d%d" % (_bucket(t), int(d))


def paged_shape_key(hd, g):
    """Shape key for the fused paged decode kernel: head dim + query
    group size (Hq/Hkv).  The pool block size is part of the *config*
    (it is what gets tuned), not the key."""
    return "hd%d_g%d" % (int(hd), int(g))


#: axes topology the launcher last built/refitted a mesh for —
#: introspection + refit bookkeeping only.  Deliberately NOT folded
#: into default lookup keys: CLI/bench sweeps run with no launcher at
#: all, so an axes-qualified launch key would never match a
#: sweep-recorded winner (populate-then-launch must round-trip).
_ambient_axes = None


def set_ambient_mesh(axes):
    """Record the live mesh topology (an ``{axis: size}`` dict, or
    None to clear) — see ``_ambient_axes``."""
    global _ambient_axes
    _ambient_axes = dict(axes) if axes else None


def ambient_axes():
    return dict(_ambient_axes) if _ambient_axes else None


def mesh_descriptor(mesh=None):
    """Mesh-topology key component.  A ready-made string passes
    through; ``None`` — the launch paths and the sweeps alike — keys
    by the live ``backend:device_count`` (a degraded pod has fewer
    devices, so its lookups naturally miss the full-size winners); an
    explicit ``{axis: size}`` dict keys by the *topology's own* device
    total plus the axes string (per-topology recordings — tests, pod
    tooling, refit invalidation)."""
    if isinstance(mesh, str):
        return mesh
    try:
        import jax
        backend, live = jax.default_backend(), jax.device_count()
    except Exception:  # noqa: BLE001 — no backend: still a usable key
        backend, live = "none", 0
    if isinstance(mesh, dict) and mesh:
        n, wild = 1, False
        for size in mesh.values():
            if int(size) == -1:
                wild = True
            else:
                n *= int(size)
        if wild:
            n = live
        return "%s:%d/%s" % (backend, n,
                             "x".join("%s%d" % (name, int(size))
                                      for name, size
                                      in sorted(mesh.items())))
    return "%s:%d" % (backend, live)


def make_key(kernel, shape_key, dtype, mesh=None):
    import numpy as np
    return "|".join((str(kernel), str(shape_key),
                     np.dtype(dtype).name, mesh_descriptor(mesh)))


# --------------------------------------------------------------------------
# The tuner core
# --------------------------------------------------------------------------

class SweepResult(object):
    """Outcome of one sweep: the winning (config, ms) — or None when
    nothing survived — plus the per-candidate ledger the CLI prints
    (``verdict`` is ``won``/``eligible``/``audit_rejected``/
    ``failed``)."""

    def __init__(self, key, winner, candidates):
        self.key = key
        self.winner = winner            # {"config":…, "ms":…} or None
        self.candidates = candidates    # [{"config","verdict","ms","findings"}]

    @property
    def audit_rejected(self):
        return [c for c in self.candidates
                if c["verdict"] == "audit_rejected"]


class KernelTuner(object):
    """Measure-validate-persist core (module docstring has the story).

    ``path`` defaults to :func:`default_cache_path`; ``vmem_kib``
    overrides the VP602 VMEM budget the audit gate applies."""

    def __init__(self, path=None, vmem_kib=None):
        # thread safety lives in WinnerCache's lock (every mutation is
        # one cache op); sweeps themselves are single-threaded drivers
        self.cache = WinnerCache(default_cache_path()
                                 if path is None else (path or None))
        self.vmem_kib = vmem_kib

    # ------------------------------------------------------------ telemetry
    def _telemetry(self, flight_kind, counter, labels, **fields):
        try:
            from veles_tpu import telemetry
            if flight_kind:
                telemetry.flight.record(flight_kind, **fields)
            if counter:
                telemetry.registry.counter(
                    "veles_tune_%s_total" % counter,
                    "kernel-autotuner %s events" % counter,
                    tuple(labels)).inc(**labels)
            telemetry.registry.gauge(
                "veles_tune_winners",
                "tuned winners currently cached").set(len(self.cache))
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass

    # --------------------------------------------------------------- lookup
    def lookup(self, kernel, shape_key, dtype, mesh=None, default=None):
        """The launch-time read: a cached winner's config dict, or
        ``default`` on miss.  Deterministic — same key, same cache,
        same answer (pinned under interpret mode in the tests)."""
        key = make_key(kernel, shape_key, dtype, mesh)
        entry = self.cache.get(key)
        result = "hit" if entry else "miss"
        self._telemetry("tune.%s" % result, "lookups",
                        {"kernel": str(kernel), "result": result},
                        key=key)
        if entry is None:
            return default
        return dict(entry["config"])

    # ---------------------------------------------------------- audit gate
    def audit_candidate(self, launches):
        """Run the VP6xx tile/VMEM audit over a candidate's launch
        descriptions.  Returns (ok, findings): any ERROR-severity
        finding (VP602 over-VMEM above all) rejects the candidate —
        it may never win, no matter what it measured."""
        from veles_tpu.analysis.numerics_audit import (
            ERROR, audit_kernel_launch)
        findings = []
        for launch in launches:
            findings.extend(
                audit_kernel_launch(launch, vmem_kib=self.vmem_kib))
        ok = not any(f.severity == ERROR for f in findings)
        return ok, findings

    # --------------------------------------------------------------- record
    def record(self, kernel, shape_key, dtype, config, ms, mesh=None,
               source="sweep", launches=None):
        """Persist one winner.  When ``launches`` are given the VP6xx
        gate applies here too — a caller (bench, bake tool) cannot
        slip an unaudited config into the cache."""
        if launches is not None:
            ok, findings = self.audit_candidate(launches)
            if not ok:
                raise ValueError(
                    "config %r for %s failed the VP6xx launch audit: %s"
                    % (config, kernel,
                       "; ".join(f.message for f in findings
                                 if f.severity == "error")))
        key = make_key(kernel, shape_key, dtype, mesh)
        entry = {"config": {str(n): int(v) for n, v in config.items()},
                 "ms": float(ms), "kernel": str(kernel),
                 "shape": str(shape_key), "mesh": mesh_descriptor(mesh),
                 "source": str(source), "audit": "clean"}
        self.cache.put(key, entry)
        self._telemetry("tune.record", None, {}, key=key,
                        config=entry["config"], ms=entry["ms"],
                        source=entry["source"])
        return entry

    # ---------------------------------------------------------------- sweep
    def sweep(self, kernel, shape_key, dtype, candidates, measure,
              mesh=None, repeats=5, warmup=2, dry_run=False,
              source="sweep"):
        """Measure ``candidates`` and persist the winner.

        ``candidates``: iterable of ``{"config": {...}, "launches":
        [...]}`` — launch descriptions feed the VP6xx gate.
        ``measure(config)`` returns seconds for ONE timed iteration
        (compile/warm-up cost lands in the ``warmup`` calls, which are
        discarded); the score is the median of the remaining
        ``repeats``.  ``dry_run`` audits and ranks without measuring
        or persisting — the CLI's candidate listing."""
        repeats = max(1, int(repeats))   # median needs >= 1 sample
        warmup = max(0, int(warmup))
        ledger = []
        best = None
        for cand in candidates:
            config = dict(cand["config"])
            ok, findings = self.audit_candidate(cand.get("launches", ()))
            row = {"config": config, "ms": None,
                   "findings": [str(f) for f in findings]}
            if not ok:
                row["verdict"] = "audit_rejected"
                ledger.append(row)
                continue
            if dry_run:
                row["verdict"] = "eligible"
                ledger.append(row)
                continue
            try:
                times = [float(measure(config))
                         for _ in range(warmup + repeats)]
            except Exception as e:  # noqa: BLE001 — VMEM overflow etc.
                row["verdict"] = "failed"
                row["error"] = "%s: %s" % (type(e).__name__, e)
                ledger.append(row)
                continue
            ms = statistics.median(times[warmup:]) * 1e3
            row["verdict"] = "eligible"
            row["ms"] = ms
            ledger.append(row)
            if best is None or ms < best["ms"]:
                best = {"config": config, "ms": ms, "row": row}
        winner = None
        if best is not None:
            best["row"]["verdict"] = "won"
            winner = self.record(kernel, shape_key, dtype,
                                 best["config"], best["ms"], mesh=mesh,
                                 source=source)
        self._telemetry(
            "tune.sweep", "sweeps", {"kernel": str(kernel)},
            key=make_key(kernel, shape_key, dtype, mesh),
            candidates=len(ledger),
            rejected=sum(1 for c in ledger
                         if c["verdict"] == "audit_rejected"),
            winner=(winner or {}).get("config"), dry_run=dry_run)
        return SweepResult(make_key(kernel, shape_key, dtype, mesh),
                           winner, ledger)

    # --------------------------------------------------------- invalidation
    def invalidate_mesh(self, mesh=None):
        """Drop every winner recorded under ``mesh``'s descriptor —
        the ``mesh.refit`` hook: a pod degraded onto fewer hosts must
        re-tune, not inherit the full-size winners."""
        desc = mesh_descriptor(mesh)
        gone = self.cache.remove(
            lambda key, entry: entry.get("mesh") == desc)
        self._telemetry("tune.invalidate", "invalidations",
                        {"reason": "mesh-refit"}, mesh=desc,
                        removed=len(gone))
        return gone

    def clear(self, kernel=None):
        if kernel is None:
            return self.cache.clear()
        gone = self.cache.remove(
            lambda key, entry: entry.get("kernel") == kernel)
        return len(gone)


# --------------------------------------------------------------------------
# Process-global tuner (what the kernels' launch-time lookups use)
# --------------------------------------------------------------------------

_tuner = None
_tuner_lock = threading.Lock()


def get_tuner():
    global _tuner
    with _tuner_lock:
        if _tuner is None:
            _tuner = KernelTuner()
        return _tuner


def reset():
    """Forget the process-global tuner (tests; also the way to pick up
    a changed ``VELES_TUNE_CACHE``)."""
    global _tuner
    with _tuner_lock:
        _tuner = None


def lookup(kernel, shape_key, dtype, mesh=None, default=None):
    """Module-level convenience over ``get_tuner().lookup`` — the
    one-liner the kernel launch paths call."""
    return get_tuner().lookup(kernel, shape_key, dtype, mesh=mesh,
                              default=default)


def on_mesh_refit(configured, fitted):
    """The launcher's elastic-mesh hook: a configured topology was
    refitted onto the live device set.  Invalidate winners tuned for
    the configured (pre-refit) topology under BOTH key forms — the
    axes-qualified form explicit recordings use, and the bare
    ``backend:count`` form the launch-time lookups use, with the count
    taken from the CONFIGURED topology's device total (the live count
    has already shrunk by the time this hook fires, so keying the
    invalidation off it would match nothing).  A wildcard (``-1``)
    configured topology has no knowable pre-refit device total — and
    the launcher never refits one (a data wildcard already absorbs the
    live count, so ``fitted == configured`` and the hook does not
    fire); if called anyway, only the ambient re-key happens."""
    set_ambient_mesh(fitted)
    if any(int(size) == -1 for size in (configured or {}).values()):
        return []
    tuner = get_tuner()
    gone = tuner.invalidate_mesh(configured)
    desc = mesh_descriptor(configured)
    if "/" in desc:
        gone += tuner.invalidate_mesh(desc.split("/", 1)[0])
    return gone
