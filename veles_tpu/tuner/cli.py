"""``veles-tpu-tune`` — sweep / list / clear the kernel-autotuner
winner cache (docs/perf.md "Autotuning", docs/cli.md).

Also reachable as ``python -m veles_tpu --tune <subcommand> ...``.

Subcommands:

``sweep``
    Measure candidate block configs for the flash forward, the split
    dq/dkv backward kernels, and the fused paged decode kernel, on
    whatever accelerator is present (interpret mode off-TPU — the
    machinery is identical, only the numbers are meaningless off
    silicon).  Every candidate passes the VP6xx tile/VMEM launch
    audit before it may win; winners persist in the cache the launch
    paths read.  ``--dry-run`` prints each candidate with its VP6xx
    verdict and persists nothing.
``list``
    Print cached winners (and quarantined entries, which are never
    served).
``clear``
    Drop winners — all of them, or ``--kernel``'s.

Exit codes: 0 = success (sweep: every requested kernel produced an
audited winner; dry-run: candidates printed); 1 = at least one
requested sweep produced NO eligible winner (all candidates failed or
were audit-rejected), or ``list --require-winners`` found an empty
cache; 2 = usage error (argparse).
"""

import argparse
import json
import sys


def _parse_kernels(spec):
    names = []
    for part in (spec or "all").split(","):
        part = part.strip()
        if part in ("all", ""):
            names += ["flash.fwd", "flash.bwd_dq", "flash.bwd_dkv",
                      "paged.decode", "paged.decode.q8"]
        elif part == "flash":
            names += ["flash.fwd", "flash.bwd_dq", "flash.bwd_dkv"]
        elif part == "paged":
            names += ["paged.decode", "paged.decode.q8"]
        elif part in ("flash.fwd", "flash.bwd_dq", "flash.bwd_dkv",
                      "paged.decode", "paged.decode.q8"):
            names.append(part)
        else:
            raise argparse.ArgumentTypeError(
                "unknown kernel %r (flash.fwd, flash.bwd_dq, "
                "flash.bwd_dkv, paged.decode, paged.decode.q8, "
                "flash, paged, all)"
                % part)
    out = []
    for n in names:        # dedup, order-preserving
        if n not in out:
            out.append(n)
    return out


def _build_parser():
    p = argparse.ArgumentParser(
        prog="veles-tpu-tune",
        description="kernel-autotuner winner cache: sweep, list, clear",
        epilog="exit codes: 0 = success; 1 = a requested sweep "
               "produced no eligible winner (all candidates failed "
               "or were VP6xx audit-rejected) or --require-winners "
               "found none; 2 = usage error")
    p.add_argument("--cache", default=None,
                   help="winner-cache JSON path (default: repo-local "
                   ".veles_tune/winners.json next to the compile "
                   "cache; VELES_TUNE_CACHE overrides)")
    sub = p.add_subparsers(dest="cmd", required=True)

    sw = sub.add_parser(
        "sweep", help="measure candidates, persist audited winners")
    sw.add_argument("--kernels", type=_parse_kernels,
                    default=_parse_kernels("all"),
                    help="comma list: flash.fwd, flash.bwd_dq, "
                    "flash.bwd_dkv, paged.decode, or the groups "
                    "flash / paged / all (default all)")
    sw.add_argument("--t", type=int, nargs="+", default=[1024],
                    help="sequence lengths for the flash sweeps")
    sw.add_argument("--d", type=int, default=128,
                    help="flash head dim (<= 64 widens the candidate "
                    "grid to 1024 blocks)")
    sw.add_argument("--paged-hd", type=int, default=128,
                    help="paged decode head dim")
    sw.add_argument("--paged-g", type=int, default=1,
                    help="paged decode query-group size (Hq/Hkv)")
    sw.add_argument("--dtype", default="bfloat16")
    sw.add_argument("--iters", type=int, default=4,
                    help="kernel calls chained per timed dispatch")
    sw.add_argument("--repeats", type=int, default=3,
                    help="timed dispatches per candidate (median "
                    "scores)")
    sw.add_argument("--warmup", type=int, default=1,
                    help="discarded warm-up dispatches (compile cost "
                    "lands here)")
    sw.add_argument("--tiny", action="store_true",
                    help="CI preset: tiny shapes + minimal iterations "
                    "(proves the machinery in interpret mode, "
                    "numbers are not meaningful)")
    sw.add_argument("--dry-run", action="store_true",
                    help="print candidates with their VP6xx verdicts; "
                    "measure and persist nothing")
    sw.add_argument("--json", default=None, metavar="PATH",
                    help="write a machine-readable sweep report")

    ls = sub.add_parser("list", help="print cached winners")
    ls.add_argument("--json", action="store_true",
                    help="dump the cache as JSON to stdout")
    ls.add_argument("--require-winners", action="store_true",
                    help="exit 1 when the cache holds no winners "
                    "(CI gate)")

    cl = sub.add_parser("clear", help="drop cached winners")
    cl.add_argument("--kernel", default=None,
                    help="only this kernel's winners (default: all, "
                    "including quarantined entries)")
    return p


def _cmd_sweep(tuner, args):
    from veles_tpu.tuner import sweeps
    if args.tiny:
        args.t = [128]
        args.d = min(args.d, 64)
        args.paged_hd = min(args.paged_hd, 64)
        args.iters, args.repeats, args.warmup = 1, 2, 1

    results = {}
    flash_kinds = [k[6:] for k in args.kernels if k.startswith("flash.")]
    if flash_kinds:
        results.update(sweeps.sweep_flash(
            tuner, ts=tuple(args.t), d=args.d, dtype=args.dtype,
            kinds=tuple(flash_kinds), iters=args.iters,
            repeats=args.repeats, warmup=args.warmup,
            dry_run=args.dry_run, log=print, source="cli-sweep"))
    if "paged.decode" in args.kernels:
        results.update(sweeps.sweep_paged(
            tuner, hd=args.paged_hd, g=args.paged_g, dtype=args.dtype,
            iters=max(args.iters, 2), repeats=args.repeats,
            warmup=args.warmup, dry_run=args.dry_run, log=print,
            source="cli-sweep"))
    if "paged.decode.q8" in args.kernels:
        # the quantized-pool variant (int8 QuantCache KV): same kernel
        # family, winners keyed at dtype int8 — exactly what a
        # cache_dtype="int8" serving launch looks up
        results.update(sweeps.sweep_paged(
            tuner, hd=args.paged_hd, g=args.paged_g, dtype="int8",
            iters=max(args.iters, 2), repeats=args.repeats,
            warmup=args.warmup, dry_run=args.dry_run, log=print,
            source="cli-sweep"))

    report = {"cache": tuner.cache.path, "dry_run": args.dry_run,
              "sweeps": []}
    failed = 0
    for ident, res in sorted(results.items(), key=str):
        rep = {"key": res.key,
               "winner": res.winner,
               "candidates": len(res.candidates),
               "audit_rejected": len(res.audit_rejected)}
        report["sweeps"].append(rep)
        for cand in res.candidates:
            marks = {"won": "WINNER", "eligible": "ok",
                     "audit_rejected": "VP6xx-REJECTED",
                     "failed": "FAILED"}
            line = "  %-28s %-15s" % (cand["config"],
                                      marks[cand["verdict"]])
            if cand.get("ms") is not None:
                line += " %9.3f ms" % cand["ms"]
            if cand["verdict"] == "audit_rejected":
                line += "  " + "; ".join(
                    f.splitlines()[0] for f in cand["findings"])
            if cand.get("error"):
                line += "  " + cand["error"]
            print(line)
        if not args.dry_run and res.winner is None:
            failed += 1
            print("  -> NO eligible winner for %s" % res.key)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print("report -> %s" % args.json)
    return 1 if failed else 0


def _cmd_list(tuner, args):
    winners = tuner.cache.items()
    quarantined = tuner.cache.quarantined()
    if args.json:
        print(json.dumps({"winners": winners,
                          "quarantined": quarantined},
                         indent=1, sort_keys=True))
    else:
        if not winners:
            print("winner cache is empty (%s)"
                  % (tuner.cache.path or "<memory-only>"))
        for key, entry in sorted(winners.items()):
            print("%-52s %-32s %9.3f ms  [%s]"
                  % (key, entry["config"], entry["ms"],
                     entry.get("source", "?")))
        for key in sorted(quarantined):
            print("%-52s QUARANTINED (never served)" % key)
    if args.require_winners and not winners:
        return 1
    return 0


def _cmd_clear(tuner, args):
    n = tuner.clear(kernel=args.kernel)
    print("cleared %d winner(s)%s" % (
        n, " for %s" % args.kernel if args.kernel else ""))
    return 0


def main(argv=None):
    args = _build_parser().parse_args(argv)
    from veles_tpu import tuner as tn
    tuner = tn.KernelTuner(path=args.cache) if args.cache \
        else tn.get_tuner()
    return {"sweep": _cmd_sweep, "list": _cmd_list,
            "clear": _cmd_clear}[args.cmd](tuner, args)


if __name__ == "__main__":
    sys.exit(main())
