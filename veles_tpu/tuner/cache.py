"""On-disk winner cache for the kernel autotuner.

One JSON file holds every tuned winner, keyed by
``kernel|shape-bucket|dtype|mesh`` (see :mod:`veles_tpu.tuner` for the
key grammar).  The file lives next to :mod:`veles_tpu.compile_cache`'s
directory by default — the two caches share economics: both persist
per-(program, topology) compilation/measurement work across process
boundaries so a scarce TPU window is spent measuring NEW configs, not
re-deriving old ones.

Robustness contract (pinned in tests/test_tuner.py):

* **atomic writes** — tmp file + ``os.replace``; a SIGKILL mid-save
  leaves the previous cache intact, never a torn file;
* **corrupt-entry quarantine** — an entry that fails validation (not a
  dict, config values not int-able, non-numeric ms) is moved to the
  file's ``quarantined`` section on load: it can never be served as a
  winner again, but stays on disk for forensics instead of being
  silently dropped;
* **corrupt-file quarantine** — a file that does not parse at all is
  moved aside to ``<path>.corrupt`` and the cache starts empty (same
  taxonomy as the snapshotter's torn-checkpoint quarantine).
"""

import json
import os
import threading

VERSION = 1


def validate_entry(entry):
    """True when ``entry`` is a servable winner: a dict whose
    ``config`` maps names to int-able values and whose ``ms`` is a
    finite number.  Everything else is quarantine fodder."""
    if not isinstance(entry, dict):
        return False
    config = entry.get("config")
    if not isinstance(config, dict) or not config:
        return False
    try:
        for name, value in config.items():
            str(name)
            int(value)
        ms = float(entry.get("ms", 0.0))
    except (TypeError, ValueError):
        return False
    return ms == ms and ms != float("inf")  # NaN/inf are not timings


class WinnerCache(object):
    """Thread-safe load/get/put/save over the winner JSON file.

    ``path=None`` keeps the cache memory-only (the
    ``VELES_TUNE_CACHE=off`` escape hatch for read-only filesystems);
    every mutation still works, nothing persists."""

    def __init__(self, path=None):
        self.path = path
        self._lock = threading.RLock()
        self._winners = {}
        self._quarantined = {}
        #: keys this instance deliberately removed (invalidation,
        #: clear) — the save-time cross-process merge must not
        #: resurrect them from another process's file
        self._removed = set()
        self._loaded = False

    # ------------------------------------------------------------ load/save
    def _read_file(self, quarantine_corrupt):
        """Parse the on-disk file → (winners, quarantined); corrupt
        entries land in quarantined.  An unparseable FILE returns
        empty — moved aside to ``*.corrupt`` only on the initial load
        (``quarantine_corrupt``), ignored during save-time merges (the
        initial-load path owns that forensics step)."""
        if not self.path or not os.path.exists(self.path):
            return {}, {}
        try:
            with open(self.path) as f:
                data = json.load(f)
            if not isinstance(data, dict):
                raise ValueError("cache root is not an object")
        except (OSError, ValueError) as e:
            if quarantine_corrupt:
                corrupt = "%s.corrupt" % self.path
                try:
                    os.replace(self.path, corrupt)
                except OSError:
                    corrupt = "<unlinkable>"
                try:
                    from veles_tpu import telemetry
                    telemetry.flight.record(
                        "tune.cache_corrupt", path=self.path,
                        moved=corrupt, error=str(e)[:200])
                except Exception:  # noqa: BLE001 — best-effort
                    pass
            return {}, {}
        winners, quarantined = {}, {}
        q = data.get("quarantined")
        if isinstance(q, dict):
            quarantined.update(q)
        w = data.get("winners")
        for key, entry in (w.items() if isinstance(w, dict) else ()):
            if validate_entry(entry):
                winners[key] = entry
            else:
                quarantined[key] = entry
        return winners, quarantined

    def _load_locked(self):
        if self._loaded:
            return
        self._loaded = True
        winners, quarantined = self._read_file(quarantine_corrupt=True)
        self._winners.update(winners)
        self._quarantined.update(quarantined)

    def _merge_disk_locked(self):
        """Fold in winners another PROCESS recorded since our load —
        a concurrent sweep of a different kernel must not be clobbered
        by our whole-file rewrite.  Our own writes win per key; our
        deliberate removals stay removed."""
        disk_w, disk_q = self._read_file(quarantine_corrupt=False)
        for key, entry in disk_w.items():
            if key not in self._winners and key not in self._removed:
                self._winners[key] = entry
        for key, entry in disk_q.items():
            if key not in self._removed:
                self._quarantined.setdefault(key, entry)

    def _save_locked(self):
        if not self.path:
            return
        self._merge_disk_locked()
        data = {"version": VERSION, "winners": self._winners}
        if self._quarantined:
            data["quarantined"] = self._quarantined
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = "%s.tmp.%d" % (self.path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)

    # --------------------------------------------------------------- access
    def get(self, key):
        with self._lock:
            self._load_locked()
            return self._winners.get(key)

    def put(self, key, entry):
        if not validate_entry(entry):
            raise ValueError("refusing to cache invalid winner %r"
                             % (entry,))
        with self._lock:
            self._load_locked()
            self._winners[key] = entry
            self._save_locked()

    def items(self):
        with self._lock:
            self._load_locked()
            return dict(self._winners)

    def quarantined(self):
        with self._lock:
            self._load_locked()
            return dict(self._quarantined)

    def __len__(self):
        with self._lock:
            self._load_locked()
            return len(self._winners)

    def remove(self, predicate):
        """Drop every winner whose (key, entry) satisfies ``predicate``;
        returns the removed keys (persisted immediately).  Applies to
        other processes' entries too — merge first, then drop."""
        with self._lock:
            self._load_locked()
            self._merge_disk_locked()
            gone = [k for k, e in self._winners.items()
                    if predicate(k, e)]
            for k in gone:
                del self._winners[k]
                self._removed.add(k)
            if gone:
                self._save_locked()
            return gone

    def clear(self):
        with self._lock:
            self._load_locked()
            self._merge_disk_locked()
            n = len(self._winners)
            self._removed.update(self._winners)
            self._removed.update(self._quarantined)
            self._winners.clear()
            self._quarantined.clear()
            self._save_locked()
            return n
