"""Candidate enumeration + measurement harnesses for the autotuner.

One function per tuned kernel family: the flash forward, the two split
backward kernels (dq, dkv — independent grids since the block-size
decoupling), and the fused paged decode kernel.  Each returns the
``{"config", "launches"}`` candidate dicts :meth:`KernelTuner.sweep`
consumes, where ``launches`` are the same VP6xx launch descriptions the
``register_kernel_audit`` hooks emit — the audit gate and the kernels
can never disagree about geometry.

Measurement uses the chained in-jit harness the bench proved out
(BENCH_SESSION.md round 2): per-dispatch timing is useless over a
tunneled device (~4-5 ms dispatch floor regardless of kernel), so
``iters`` kernel calls are chained inside ONE jit dispatch — each call
feeds the previous output back as q — and the dispatch cost amortizes
away.  Off-accelerator the same harness runs in interpret mode (CI's
``tune-smoke`` proves the machinery; the numbers only mean something
on silicon).
"""

import functools
import time

#: ranked block-size grid per head-dim regime: the d=128 grid the
#: flashtune phase swept, widened with 1024-blocks for the d<=64 VMEM
#: regime (half-size slabs — 1024 fits and measured fastest there)
_SIZES_D128 = (512, 256, 128)
_SIZES_D64 = (1024, 512, 256, 128)


def _rank_pairs(pairs, d):
    """tools/cost_model.predict_flashtune_order's ranking, inlined so
    library code never imports the repo-root ``tools`` package: larger
    blocks amortize the softmax/rescale bookkeeping between inner
    matmuls, square blocks win ties (cleaner causal diagonals)."""
    def overhead(pair):
        bq, bk = pair
        return ((bq * 4 + 200) / (2.0 * bq * bk * d)
                + (0 if bq == bk else 1e-9))
    return sorted(pairs, key=overhead)


def flash_candidates(kind, t, d, dtype="bfloat16", causal=True,
                     window=None):
    """Ranked candidates for one flash kernel.  ``kind`` is one of
    ``fwd``/``bwd_dq``/``bwd_dkv``; configs use plain block_q/block_k
    names (the kernel key carries which grid they bind to).  Blocks are
    capped at the padded sequence length — oversized candidates would
    all clamp to the same real geometry and measure as duplicates."""
    from veles_tpu.ops.pallas import flash

    sizes = _SIZES_D64 if d <= 64 else _SIZES_D128
    cap = max(128, -(-int(t) // 128) * 128)
    sizes = sorted({min(s, cap) for s in sizes}, reverse=True)
    kernel = {"fwd": "forward", "bwd_dq": "bwd_dq",
              "bwd_dkv": "bwd_dkv"}[kind]
    out = []
    for bq, bk in _rank_pairs([(bq, bk) for bq in sizes for bk in sizes],
                              d):
        blocks = ({"block_q": bq, "block_k": bk} if kind == "fwd" else
                  {"block_q_%s" % kind[4:]: bq,
                   "block_k_%s" % kind[4:]: bk})
        out.append({
            "config": {"block_q": bq, "block_k": bk},
            "launches": flash.audit_launch(
                t, t, d, dtype=dtype, causal=causal, window=window,
                kernels=(kernel,), **blocks),
        })
    return out


def paged_candidates(hd, g=1, dtype="bfloat16", nbm=32):
    """Candidates for the fused paged decode kernel: the KV pool block
    size (how many keys one grid step streams — the vLLM block) and
    the q-group sublane pad.  ``dtype`` is the POOL dtype — ``int8``
    enumerates the quantized-pool variant (QuantCache: int8 K/V tiles
    + per-position scale tiles; q stays bf16), whose audit launches
    carry the extra scale blocks."""
    import numpy as np

    from veles_tpu.ops.pallas import paged

    quant = np.dtype(dtype) == np.dtype(np.int8)
    # int8 tiles want 32 sublanes on silicon; smaller blocks stay in
    # the grid (VP600 warns, never rejects) so interpret-mode CI still
    # proves the ranking machinery over the same budget trade-off
    sizes = (64, 32, 16) if quant else (32, 16, 8)
    out = []
    for bs in sizes:
        for gp in sorted({max(int(g), paged._MIN_G), 32}):
            out.append({
                "config": {"block": bs, "block_g": gp},
                "launches": paged.audit_launch(hd, bs, g=gp,
                                               dtype=dtype, nbm=nbm),
            })
    return out


# --------------------------------------------------------------------------
# Measurement harnesses
# --------------------------------------------------------------------------

def _chain(fn, iters, *args):
    """jit(fn chained ``iters`` times feeding dq/out back as q); the
    returned thunk runs one chained dispatch and returns wall seconds.
    The thunk must be built ONCE per config and called repeatedly —
    jax's jit cache keys on the callable's identity, so a rebuilt
    wrapper would retrace+recompile inside every timed call (the
    measure factories below memoize per config; the first, compiling,
    call lands in the sweep's discarded warm-up)."""
    import jax
    from jax import lax

    @jax.jit
    def chained(q, *rest):
        def body(y, _):
            return fn(y, *rest), None
        y, _ = lax.scan(body, q, None, length=iters)
        return y

    def run():
        t0 = time.perf_counter()
        jax.block_until_ready(chained(*args))
        return (time.perf_counter() - t0) / iters
    return run


def flash_measure(kind, t, d, dtype="bfloat16", b=None, h=8, iters=4,
                  causal=True, window=None, interpret=None, seed=0):
    """Measure-thunk factory for one flash kernel: returns
    ``measure(config) -> seconds`` for :meth:`KernelTuner.sweep`.

    Backward kernels are timed in ISOLATION by differentiating w.r.t.
    only their outputs' inputs — ``bwd_dq`` takes grad over q (XLA
    dead-codes the unused dkv pallas_call), ``bwd_dkv`` over (k, v)
    (the dq call dies) — so a dq candidate's score never includes dkv
    time.  The forward runs in both (its residuals feed the backward);
    candidates share one pinned forward config, so the delta between
    candidates is purely the tuned kernel."""
    import jax
    from veles_tpu.ops.pallas.flash import flash_attention

    if b is None:
        b = 4 if t <= 2048 else 1
    key = jax.random.key(seed)
    q, k, v = (jax.random.normal(kk, (b, h, t, d)).astype(dtype) * 0.1
               for kk in jax.random.split(key, 3))
    thunks = {}   # config -> compiled chained thunk (see _chain)

    def measure(config):
        bq, bk = int(config["block_q"]), int(config["block_k"])
        run = thunks.get((bq, bk))
        if run is not None:
            return run()
        kwargs = dict(causal=causal, window=window, interpret=interpret)
        if kind == "fwd":
            kwargs.update(block_q=bq, block_k=bk)
        else:
            # pin the forward (and the sibling backward kernel) to the
            # defaults so only the candidate's grid varies
            kwargs.update({"block_q_%s" % kind[4:]: bq,
                           "block_k_%s" % kind[4:]: bk})
        attn = functools.partial(flash_attention, **kwargs)
        if kind == "fwd":
            fn = lambda q_, k_, v_: attn(q_, k_, v_)  # noqa: E731
        elif kind == "bwd_dq":
            fn = jax.grad(
                lambda q_, k_, v_: attn(q_, k_, v_).sum(), argnums=0)
        elif kind == "bwd_dkv":
            def fn(q_, k_, v_):
                dk, dv = jax.grad(
                    lambda q2, k2, v2: attn(q2, k2, v2).sum(),
                    argnums=(1, 2))(q_, k_, v_)
                # keep BOTH outputs live through a cheap reduction that
                # still has q's shape for the chain feed-back
                return q_ + (dk.sum() + dv.sum()).astype(q_.dtype)
        else:
            raise ValueError("kind must be fwd/bwd_dq/bwd_dkv, got %r"
                             % (kind,))
        thunks[(bq, bk)] = run = _chain(fn, iters, q, k, v)
        return run()
    return measure


def paged_measure(hd, g=1, dtype="bfloat16", slots=8, pool_blocks=32,
                  hkv=4, iters=8, interpret=None, seed=0):
    """Measure-thunk factory for the fused paged decode kernel.  The
    pool layout depends on the candidate's block size, so inputs are
    built per config (pool token budget held constant — the real
    serving trade-off: more, smaller blocks vs fewer, larger ones).
    ``dtype="int8"`` measures the quantized-pool variant: the pools
    are QuantCache pairs (random f32 K/V quantized through the real
    ``quantize_kv``), q stays bf16."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from veles_tpu.ops.attention import QuantCache, quantize_kv
    from veles_tpu.ops.pallas.paged import paged_attention_decode

    quant = np.dtype(dtype) == np.dtype(np.int8)
    tokens = pool_blocks * 16     # constant budget across candidates
    hq = hkv * g
    thunks = {}   # config -> (jitted fn, inputs) built once per config

    def measure(config):
        bs = int(config["block"])
        gp = int(config.get("block_g", 0)) or None
        cached = thunks.get((bs, gp))
        if cached is None:
            npool = max(2, tokens // bs + 1)
            nbm = max(2, tokens // bs)
            key = jax.random.key(seed)
            kq, kk, kv = jax.random.split(key, 3)
            fdt = jnp.bfloat16 if quant else dtype
            q = jax.random.normal(kq, (slots, hq, hd)).astype(fdt) * 0.1
            pool_k = jax.random.normal(
                kk, (npool, hkv, bs, hd)).astype(jnp.float32) * 0.1
            pool_v = jax.random.normal(
                kv, (npool, hkv, bs, hd)).astype(jnp.float32) * 0.1
            if quant:
                pool_k = QuantCache(*quantize_kv(pool_k))
                pool_v = QuantCache(*quantize_kv(pool_v))
            else:
                pool_k = pool_k.astype(dtype)
                pool_v = pool_v.astype(dtype)
            table = (1 + (jnp.arange(slots * nbm)
                          % (npool - 1))).reshape(
                slots, nbm).astype(jnp.int32)
            pos = jnp.full((slots,), nbm * bs - 1, jnp.int32)
            fn = jax.jit(functools.partial(
                paged_attention_decode, interpret=interpret,
                block_g=gp))
            thunks[(bs, gp)] = cached = (
                fn, (q, pool_k, pool_v, table, pos))
        fn, args = cached
        # decode is one tiny dispatch; average a few inside the timer
        # (the first, compiling, call lands in the discarded warm-up)
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(fn(*args))
        return (time.perf_counter() - t0) / iters
    return measure


# --------------------------------------------------------------------------
# Whole-family sweeps (the CLI and bench phase both drive these)
# --------------------------------------------------------------------------

FLASH_KINDS = ("fwd", "bwd_dq", "bwd_dkv")


def sweep_flash(tuner, ts=(1024,), d=128, dtype="bfloat16", kinds=None,
                iters=4, repeats=3, warmup=1, causal=True,
                interpret=None, dry_run=False, mesh=None, log=None,
                source="sweep"):
    """Sweep the flash forward + split backward kernels over the given
    sequence lengths.  Returns ``{(kind, t): SweepResult}``."""
    from veles_tpu.tuner import flash_shape_key
    results = {}
    for kind in (kinds or FLASH_KINDS):
        for t in ts:
            cands = flash_candidates(kind, t, d, dtype=dtype,
                                     causal=causal)
            measure = (None if dry_run else
                       flash_measure(kind, t, d, dtype=dtype,
                                     iters=iters, causal=causal,
                                     interpret=interpret))
            res = tuner.sweep("flash.%s" % kind, flash_shape_key(t, d),
                              dtype, cands, measure, mesh=mesh,
                              repeats=repeats, warmup=warmup,
                              dry_run=dry_run, source=source)
            results[(kind, t)] = res
            if log:
                w = res.winner
                log("flash.%s t=%d d=%d: %s (candidates %d, "
                    "audit-rejected %d)"
                    % (kind, t, d,
                       "winner %r %.3f ms" % (w["config"], w["ms"])
                       if w else ("dry run" if dry_run
                                  else "no winner"),
                       len(res.candidates), len(res.audit_rejected)))
    return results


def sweep_paged(tuner, hd=128, g=1, dtype="bfloat16", iters=8,
                repeats=3, warmup=1, interpret=None, dry_run=False,
                mesh=None, log=None, source="sweep"):
    """Sweep the fused paged decode kernel's pool block + q-group pad.
    Winners key by (kernel ``paged.decode``, shape, POOL dtype) — the
    int8 QuantCache flavor is the same kernel family at dtype
    ``int8``, swept via ``dtype="int8"`` (the launch path's
    ``preferred_pool_block``/``_resolve_block_g`` look up with the
    pool's own dtype, so serving finds the right regime's winner
    automatically)."""
    from veles_tpu.tuner import paged_shape_key
    cands = paged_candidates(hd, g=g, dtype=dtype)
    measure = (None if dry_run else
               paged_measure(hd, g=g, dtype=dtype, iters=iters,
                             interpret=interpret))
    res = tuner.sweep("paged.decode", paged_shape_key(hd, g), dtype,
                      cands, measure, mesh=mesh, repeats=repeats,
                      warmup=warmup, dry_run=dry_run, source=source)
    if log:
        w = res.winner
        log("paged.decode[%s] hd=%d g=%d: %s (candidates %d, "
            "audit-rejected %d)"
            % (dtype, hd, g,
               "winner %r %.3f ms" % (w["config"], w["ms"])
               if w else ("dry run" if dry_run else "no winner"),
               len(res.candidates), len(res.audit_rejected)))
    return {("paged", dtype, hd): res}
