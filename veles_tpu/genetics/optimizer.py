"""GeneticsOptimizer — evolve Range-tagged workflow configs
(ref: veles/genetics/optimization_workflow.py:70-298; the reference's
``--optimize size[:generations]`` CLI forked a full veles process per
fitness evaluation and distributed them over slaves; here evaluation is a
callable — typically "build StandardWorkflow from config, train, return
-validation_error" — run sequentially or handed to any executor)."""

from veles_tpu.genetics.core import Population, extract_ranges
from veles_tpu.logger import Logger


class GeneticsOptimizer(Logger):
    """Evolve a config dict whose tunable leaves are ``Range`` objects.

    :param config: nested dict with Range leaves
    :param evaluate: callable(config_dict) -> fitness (higher is better)
    :param size: population size; ``generations``: how many to run
    """

    def __init__(self, config, evaluate, size=20, generations=10,
                 executor_map=None, early_stop_eps=None,
                 **population_kwargs):
        super(GeneticsOptimizer, self).__init__()
        self.config = config
        self.paths = extract_ranges(config)
        if not self.paths:
            raise ValueError("config has no Range leaves to optimize")
        self.evaluate = evaluate
        self.generations = generations
        #: optional parallel map(fn, iterable) — defaults to builtin map
        self.executor_map = executor_map or (lambda f, xs: list(map(f, xs)))
        #: stop early when the population's fitness spread drops below
        #: this (None = run all generations)
        self.early_stop_eps = early_stop_eps
        self.population = Population(size, len(self.paths),
                                     **population_kwargs)
        self.history = []
        self.stats_history = []

    def run(self):
        for gen in range(self.generations):
            todo = [c for c in self.population.chromosomes
                    if c.fitness is None]
            configs = [c.config_for(self.config, self.paths) for c in todo]
            fits = self.executor_map(self.evaluate, configs)
            for c, f in zip(todo, fits):
                c.fitness = float(f)
            best = self.population.best
            self.history.append(best.fitness)
            self.stats_history.append(self.population.stats())
            self.info("generation %d: best fitness %.6f (%s)",
                      gen, best.fitness,
                      {"/".join(p): r.decode(best.values[i])
                       for i, (p, r) in enumerate(self.paths)})
            if self.early_stop_eps is not None and \
                    self.population.converged(self.early_stop_eps):
                self.info("population converged (std <= %g) — stopping "
                          "after generation %d", self.early_stop_eps, gen)
                break
            if gen < self.generations - 1:
                self.population.evolve()
        return self.best_config

    @property
    def best_config(self):
        best = self.population.best
        return None if best is None else best.config_for(self.config,
                                                         self.paths)
