"""Distributed GA fitness — a stdlib-HTTP work queue.

The reference distributed genetic-optimization fitness evaluations across
its master/slave cluster over Twisted
(veles/genetics/optimization_workflow.py:181-216: the master forked a
training process per chromosome and slaves pulled jobs).  The TPU-era
equivalent keeps the shape — a coordinator owns the population, workers
anywhere pull one chromosome at a time, train the workflow locally and
post the fitness back — but the wire is plain HTTP/JSON on the stdlib
server (no Twisted, no pickle on the wire; the payload is the flat
{dotted-config-path: value} dict of Range leaves).

Coordinator:  ``--optimize SIZE:GENS --optimize-workers N@HOST:PORT``
              (N local evaluator threads pull from the SAME queue, so
              local and remote capacity compose; N=0 = remote-only)
Worker:       ``python -m veles_tpu <workflow> <config>
              --optimize-worker HOST:PORT`` on any host that has the
              workflow files (the same requirement the reference's
              slaves had).

Fault tolerance: a job leased to a worker that dies re-enters the queue
after ``job_timeout`` (the child-training watchdog); results arriving
twice are ignored (first one wins)."""

import collections
import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu.logger import Logger


class FitnessQueue(Logger):
    """Coordinator side: job queue + HTTP endpoints.

    GET  /job     -> {"id": int, "leaves": {...}} | {"idle": true}
                     | {"done": true}
    POST /result  body {"id": int, "fitness": float} -> {"ok": true}
    """

    def __init__(self, host="0.0.0.0", port=0, job_timeout=1800.0):
        super(FitnessQueue, self).__init__()
        self.host, self.port = host, int(port)
        self.job_timeout = float(job_timeout)
        self._lock = threading.Lock()
        self._pending = collections.deque()
        self._inflight = {}            # id -> (job, lease deadline)
        self._results = {}             # id -> fitness
        self._jobs = {}                # id -> job (for requeue)
        self._next_id = 0
        self._shutdown = False
        self._server = None

    # ------------------------------------------------------------ queue
    def _take(self):
        with self._lock:
            if self._shutdown:
                return {"done": True}
            now = time.monotonic()
            for jid, (job, deadline) in list(self._inflight.items()):
                if now > deadline:     # worker died — re-lease
                    del self._inflight[jid]
                    self._pending.append(job)
                    self.warning("job %d lease expired — requeued", jid)
            if not self._pending:
                return {"idle": True}
            job = self._pending.popleft()
            self._inflight[job["id"]] = (job,
                                         now + self.job_timeout)
            return job

    def _post_result(self, jid, fitness):
        with self._lock:
            if jid not in self._jobs or jid in self._results:
                return                 # duplicate / unknown: first wins
            self._results[jid] = float(fitness)
            self._inflight.pop(jid, None)

    # ----------------------------------------------------------- server
    def start(self):
        queue = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, obj):
                body = json.dumps(obj).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path != "/job":
                    self.send_error(404)
                    return
                self._send(queue._take())

            def do_POST(self):
                if self.path != "/result":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(length))
                    queue._post_result(int(req["id"]),
                                       float(req["fitness"]))
                except (ValueError, KeyError, TypeError) as e:
                    self.send_error(400, str(e))
                    return
                self._send({"ok": True})

            def log_message(self, fmt, *args):
                queue.debug("queue http: " + fmt, *args)

        class Server(ThreadingHTTPServer):
            request_queue_size = 128
            daemon_threads = True

        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        self.info("fitness queue serving on %s:%d", self.host, self.port)

    def shutdown(self):
        """Tell pollers to exit, then stop serving (keep the socket open
        briefly so waiting workers can read the ``done`` signal)."""
        with self._lock:
            self._shutdown = True

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server = None

    # -------------------------------------------------------------- map
    def map(self, evaluate_leaves, leaves_list, local_workers=0):
        """Evaluate every leaves-dict; block until all fitnesses arrive
        (from local threads and/or remote workers).  Returns fitnesses
        in input order."""
        with self._lock:
            ids = []
            for leaves in leaves_list:
                job = {"id": self._next_id, "leaves": leaves}
                self._next_id += 1
                self._jobs[job["id"]] = job
                self._pending.append(job)
                ids.append(job["id"])

        def local_loop():
            while True:
                job = self._take()
                if "id" in job:
                    self._post_result(job["id"],
                                      evaluate_leaves(job["leaves"]))
                    continue
                if job.get("done"):
                    return
                # idle — but a remote lease may still expire and requeue,
                # so stay alive until every job of THIS map resolved
                with self._lock:
                    if all(jid in self._results for jid in ids):
                        return
                time.sleep(0.2)

        threads = [threading.Thread(target=local_loop, daemon=True)
                   for _ in range(local_workers)]
        for t in threads:
            t.start()
        while True:
            with self._lock:
                if all(jid in self._results for jid in ids):
                    break
            time.sleep(0.05)
        for t in threads:
            t.join()
        with self._lock:
            return [self._results[jid] for jid in ids]


# ------------------------------------------------------------------ worker
def run_worker(address, evaluate_leaves, poll_interval=1.0,
               max_connect_failures=30):
    """Worker loop: pull jobs from ``address`` ("HOST:PORT"), evaluate,
    post fitness.  Returns the number of jobs evaluated.  Exits when the
    coordinator signals ``done`` or goes away for
    ``max_connect_failures`` consecutive polls."""
    base = "http://%s" % address
    evaluated = 0
    failures = 0
    while True:
        try:
            with urllib.request.urlopen(base + "/job", timeout=30) as r:
                job = json.loads(r.read())
            failures = 0
        except OSError:
            failures += 1
            if failures >= max_connect_failures:
                return evaluated       # coordinator is gone
            time.sleep(poll_interval)
            continue
        if job.get("done"):
            return evaluated
        if "id" not in job:
            time.sleep(poll_interval)
            continue
        fitness = evaluate_leaves(job["leaves"])
        body = json.dumps({"id": job["id"],
                           "fitness": fitness}).encode()
        req = urllib.request.Request(
            base + "/result", body,
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=30).read()
        except OSError:
            pass                       # lease expiry will requeue it
        evaluated += 1
