"""GA core: Range-tagged config leaves, chromosomes, population evolution
(ref: veles/genetics/core.py — float chromosomes, roulette/tournament
selection, crossover + mutation pipelines `core.py:371-430`).

Kept from the reference: ``Range`` wrappers make any config leaf tunable;
selection = roulette or tournament; crossover = uniform / single-point /
blend (arithmetic); mutation = gaussian jitter (float) / bit flips
(binary); elitism; **gray-code binary chromosomes**
(``encoding="gray"`` — the reference's binary encoding, where adjacent
values differ by one bit so a single mutation moves the phenotype
minimally).  Dropped: process forking inside the GA (fitness evaluation
is a callable — the CLI wires it to a full training subprocess)."""

import numpy as np

from veles_tpu import prng
from veles_tpu.logger import Logger


class Range(object):
    """Marks a tunable config value: Range(min, max) or
    Range(min, max, int) (ref genetics/config.py)."""

    def __init__(self, min_value, max_value, vtype=float):
        self.min = min_value
        self.max = max_value
        self.vtype = vtype

    def decode(self, unit_value):
        v = self.min + (self.max - self.min) * float(unit_value)
        return int(round(v)) if self.vtype is int else v

    def __repr__(self):
        return "Range(%s, %s, %s)" % (self.min, self.max,
                                      self.vtype.__name__)


def extract_ranges(config, path=()):
    """Walk a nested dict; yield (path, Range) for every tunable leaf."""
    out = []
    for k, v in config.items():
        if isinstance(v, Range):
            out.append((path + (k,), v))
        elif isinstance(v, dict):
            out.extend(extract_ranges(v, path + (k,)))
    return out


def apply_genes(config, genes):
    """Return a deep copy of ``config`` with Range leaves replaced by the
    decoded gene values.  ``genes`` is {path tuple: unit float}."""
    return _apply(config, genes, ())


def _apply(config, genes, path):
    out = {}
    for k, v in config.items():
        p = path + (k,)
        if isinstance(v, Range):
            out[k] = v.decode(genes[p])
        elif isinstance(v, dict):
            out[k] = _apply(v, genes, p)
        else:
            out[k] = v
    return out


def gray_decode(bits):
    """[n_genes, nbits] 0/1 gray-code bits → unit floats [n_genes]."""
    bits = np.asarray(bits, np.uint8)
    binary = np.bitwise_xor.accumulate(bits, axis=1)   # gray → binary
    weights = 2.0 ** np.arange(bits.shape[1] - 1, -1, -1)
    return (binary @ weights) / (2.0 ** bits.shape[1] - 1.0)


def gray_encode(values, nbits):
    """Unit floats [n] → gray-code bits [n, nbits]."""
    ints = np.round(np.clip(values, 0.0, 1.0)
                    * (2 ** nbits - 1)).astype(np.int64)
    gray = ints ^ (ints >> 1)
    shifts = np.arange(nbits - 1, -1, -1)
    return ((gray[:, None] >> shifts[None, :]) & 1).astype(np.uint8)


class Chromosome(object):
    """Unit-interval gene vector + fitness (ref core.py:133).  Two
    representations: a float vector, or gray-code bits ([n_genes, nbits]
    uint8 — the reference's binary chromosomes) from which the float
    view is decoded."""

    def __init__(self, values=None, bits=None):
        if bits is not None:
            self.bits = np.asarray(bits, np.uint8)
            self.values = gray_decode(self.bits)
        else:
            self.bits = None
            self.values = np.clip(np.asarray(values, np.float64), 0.0, 1.0)
        self.fitness = None

    def config_for(self, config, paths):
        genes = {p: self.values[i] for i, (p, _) in enumerate(paths)}
        return _apply(config, genes, ())


class Population(Logger):
    """Evolving population (ref core.py:371-430)."""

    def __init__(self, size, n_genes, selection="roulette",
                 crossover="uniform", mutation_rate=0.1,
                 mutation_sigma=0.15, elite=1, encoding="float",
                 nbits=16, rng_name="genetics"):
        super(Population, self).__init__()
        if encoding not in ("float", "gray"):
            raise ValueError("encoding must be 'float' or 'gray'")
        self.size = size
        self.n_genes = n_genes
        self.selection = selection
        self.crossover = crossover
        self.mutation_rate = mutation_rate
        self.mutation_sigma = mutation_sigma
        self.elite = elite
        self.encoding = encoding
        self.nbits = nbits
        self.rng = prng.get(rng_name)
        self.generation = 0
        if encoding == "gray":
            g = self.rng.numpy()
            self.chromosomes = [
                Chromosome(bits=g.integers(0, 2, (n_genes, nbits)))
                for _ in range(size)]
        else:
            self.chromosomes = [
                Chromosome(self.rng.uniform(size=n_genes))
                for _ in range(size)]

    @property
    def best(self):
        scored = [c for c in self.chromosomes if c.fitness is not None]
        return max(scored, key=lambda c: c.fitness) if scored else None

    # -- selection ----------------------------------------------------------
    def _select(self):
        g = self.rng.numpy()
        fits = np.array([c.fitness for c in self.chromosomes], np.float64)
        if self.selection == "tournament":
            k = max(2, self.size // 5)
            idx = g.choice(self.size, size=k, replace=False)
            return self.chromosomes[idx[np.argmax(fits[idx])]]
        # roulette on rank (robust to negative/flat fitness)
        order = np.argsort(fits)
        ranks = np.empty(self.size)
        ranks[order] = np.arange(1, self.size + 1)
        p = ranks / ranks.sum()
        return self.chromosomes[g.choice(self.size, p=p)]

    # -- crossover ----------------------------------------------------------
    def _cross(self, a, b):
        if self.encoding == "gray":
            return self._cross_bits(a, b)
        g = self.rng.numpy()
        if self.crossover == "single_point":
            cut = g.integers(1, self.n_genes) if self.n_genes > 1 else 0
            child = np.concatenate([a.values[:cut], b.values[cut:]])
        elif self.crossover == "blend":
            w = g.uniform(size=self.n_genes)
            child = w * a.values + (1 - w) * b.values
        else:  # uniform
            mask = g.uniform(size=self.n_genes) < 0.5
            child = np.where(mask, a.values, b.values)
        return Chromosome(child)

    def _cross_bits(self, a, b):
        """Bit-level crossover over the flattened gray genome (ref binary
        chromosome crossover)."""
        g = self.rng.numpy()
        fa, fb = a.bits.ravel(), b.bits.ravel()
        if self.crossover == "single_point":
            cut = g.integers(1, fa.size) if fa.size > 1 else 0
            child = np.concatenate([fa[:cut], fb[cut:]])
        else:  # uniform (blend has no bit analogue; uniform is closest)
            mask = g.uniform(size=fa.size) < 0.5
            child = np.where(mask, fa, fb)
        return Chromosome(bits=child.reshape(self.n_genes, self.nbits))

    def _mutate(self, c):
        g = self.rng.numpy()
        if self.encoding == "gray":
            # bit flips; gray code keeps single flips phenotypically local
            flips = g.uniform(size=c.bits.shape) < \
                self.mutation_rate / self.nbits
            c.bits = np.where(flips, 1 - c.bits, c.bits).astype(np.uint8)
            c.values = gray_decode(c.bits)
            return c
        mask = g.uniform(size=self.n_genes) < self.mutation_rate
        jitter = g.normal(0, self.mutation_sigma, self.n_genes)
        c.values = np.clip(np.where(mask, c.values + jitter, c.values),
                           0.0, 1.0)
        return c

    # -- one generation -----------------------------------------------------
    def evolve(self):
        """Build the next generation (requires all fitnesses set)."""
        if any(c.fitness is None for c in self.chromosomes):
            raise ValueError("evolve() before all fitnesses evaluated")
        elites = sorted(self.chromosomes,
                        key=lambda c: -c.fitness)[:self.elite]
        nxt = []
        for src in elites:
            copy = (Chromosome(bits=src.bits.copy())
                    if src.bits is not None
                    else Chromosome(src.values.copy()))
            copy.fitness = src.fitness   # elites keep their own score
            nxt.append(copy)
        while len(nxt) < self.size:
            child = self._mutate(self._cross(self._select(), self._select()))
            nxt.append(child)
        self.chromosomes = nxt
        self.generation += 1

    # -- diagnostics --------------------------------------------------------
    def stats(self):
        """Best/mean/std of the current generation's fitnesses."""
        fits = np.array([c.fitness for c in self.chromosomes
                         if c.fitness is not None], np.float64)
        if not len(fits):
            return None
        return {"generation": self.generation, "best": float(fits.max()),
                "mean": float(fits.mean()), "std": float(fits.std())}

    def converged(self, eps=1e-6):
        """True when the scored population's fitness spread collapsed —
        the GA has nothing left to exploit (early-stop signal)."""
        fits = [c.fitness for c in self.chromosomes
                if c.fitness is not None]
        return (len(fits) == len(self.chromosomes)
                and float(np.std(fits)) <= eps)
