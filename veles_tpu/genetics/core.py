"""GA core: Range-tagged config leaves, chromosomes, population evolution
(ref: veles/genetics/core.py — float chromosomes, roulette/tournament
selection, crossover + mutation pipelines `core.py:371-430`).

Kept from the reference: ``Range`` wrappers make any config leaf tunable;
selection = roulette or tournament; crossover = uniform / single-point /
blend (arithmetic); mutation = gaussian jitter / uniform reset; elitism.
Dropped: gray-code binary chromosomes (the float encoding dominates in the
reference's own defaults); process forking (fitness evaluation is a
callable — the CLI wires it to a full training run)."""

import numpy as np

from veles_tpu import prng
from veles_tpu.logger import Logger


class Range(object):
    """Marks a tunable config value: Range(min, max) or
    Range(min, max, int) (ref genetics/config.py)."""

    def __init__(self, min_value, max_value, vtype=float):
        self.min = min_value
        self.max = max_value
        self.vtype = vtype

    def decode(self, unit_value):
        v = self.min + (self.max - self.min) * float(unit_value)
        return int(round(v)) if self.vtype is int else v

    def __repr__(self):
        return "Range(%s, %s, %s)" % (self.min, self.max,
                                      self.vtype.__name__)


def extract_ranges(config, path=()):
    """Walk a nested dict; yield (path, Range) for every tunable leaf."""
    out = []
    for k, v in config.items():
        if isinstance(v, Range):
            out.append((path + (k,), v))
        elif isinstance(v, dict):
            out.extend(extract_ranges(v, path + (k,)))
    return out


def apply_genes(config, genes):
    """Return a deep copy of ``config`` with Range leaves replaced by the
    decoded gene values.  ``genes`` is {path tuple: unit float}."""
    return _apply(config, genes, ())


def _apply(config, genes, path):
    out = {}
    for k, v in config.items():
        p = path + (k,)
        if isinstance(v, Range):
            out[k] = v.decode(genes[p])
        elif isinstance(v, dict):
            out[k] = _apply(v, genes, p)
        else:
            out[k] = v
    return out


class Chromosome(object):
    """Unit-interval float vector + fitness (ref core.py:133)."""

    def __init__(self, values):
        self.values = np.clip(np.asarray(values, np.float64), 0.0, 1.0)
        self.fitness = None

    def config_for(self, config, paths):
        genes = {p: self.values[i] for i, (p, _) in enumerate(paths)}
        return _apply(config, genes, ())


class Population(Logger):
    """Evolving population (ref core.py:371-430)."""

    def __init__(self, size, n_genes, selection="roulette",
                 crossover="uniform", mutation_rate=0.1,
                 mutation_sigma=0.15, elite=1, rng_name="genetics"):
        super(Population, self).__init__()
        self.size = size
        self.n_genes = n_genes
        self.selection = selection
        self.crossover = crossover
        self.mutation_rate = mutation_rate
        self.mutation_sigma = mutation_sigma
        self.elite = elite
        self.rng = prng.get(rng_name)
        self.generation = 0
        self.chromosomes = [
            Chromosome(self.rng.uniform(size=n_genes))
            for _ in range(size)]

    @property
    def best(self):
        scored = [c for c in self.chromosomes if c.fitness is not None]
        return max(scored, key=lambda c: c.fitness) if scored else None

    # -- selection ----------------------------------------------------------
    def _select(self):
        g = self.rng.numpy()
        fits = np.array([c.fitness for c in self.chromosomes], np.float64)
        if self.selection == "tournament":
            k = max(2, self.size // 5)
            idx = g.choice(self.size, size=k, replace=False)
            return self.chromosomes[idx[np.argmax(fits[idx])]]
        # roulette on rank (robust to negative/flat fitness)
        order = np.argsort(fits)
        ranks = np.empty(self.size)
        ranks[order] = np.arange(1, self.size + 1)
        p = ranks / ranks.sum()
        return self.chromosomes[g.choice(self.size, p=p)]

    # -- crossover ----------------------------------------------------------
    def _cross(self, a, b):
        g = self.rng.numpy()
        if self.crossover == "single_point":
            cut = g.integers(1, self.n_genes) if self.n_genes > 1 else 0
            child = np.concatenate([a.values[:cut], b.values[cut:]])
        elif self.crossover == "blend":
            w = g.uniform(size=self.n_genes)
            child = w * a.values + (1 - w) * b.values
        else:  # uniform
            mask = g.uniform(size=self.n_genes) < 0.5
            child = np.where(mask, a.values, b.values)
        return Chromosome(child)

    def _mutate(self, c):
        g = self.rng.numpy()
        mask = g.uniform(size=self.n_genes) < self.mutation_rate
        jitter = g.normal(0, self.mutation_sigma, self.n_genes)
        c.values = np.clip(np.where(mask, c.values + jitter, c.values),
                           0.0, 1.0)
        return c

    # -- one generation -----------------------------------------------------
    def evolve(self):
        """Build the next generation (requires all fitnesses set)."""
        if any(c.fitness is None for c in self.chromosomes):
            raise ValueError("evolve() before all fitnesses evaluated")
        elites = sorted(self.chromosomes,
                        key=lambda c: -c.fitness)[:self.elite]
        nxt = []
        for src in elites:
            copy = Chromosome(src.values.copy())
            copy.fitness = src.fitness   # elites keep their own score
            nxt.append(copy)
        while len(nxt) < self.size:
            child = self._mutate(self._cross(self._select(), self._select()))
            nxt.append(child)
        self.chromosomes = nxt
        self.generation += 1
