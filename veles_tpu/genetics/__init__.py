"""Genetic hyperparameter optimization (ref: veles/genetics/ — SURVEY §2.8)."""

from veles_tpu.genetics.core import Chromosome, Population, Range
from veles_tpu.genetics.optimizer import GeneticsOptimizer

__all__ = ["Range", "Chromosome", "Population", "GeneticsOptimizer"]
