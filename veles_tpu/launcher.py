"""Launcher — run-mode detection and process lifecycle
(ref veles/launcher.py:100: master/slave/standalone mode selection, reactor
ownership, graphics + web-status startup, `boot()=initialize()+run()`).

The reference's three modes map onto SPMD:

* **standalone** — one process, one device (or a local mesh).
* **spmd** — every host runs the *same* program; ``jax.distributed``
  over DCN replaces the Twisted TCP control plane, and the gradient
  exchange is the ``psum`` XLA inserts over ICI (no master/slave
  asymmetry — "master" duties like snapshotting and dashboards fall to
  process 0).

The reference's SSH slave spawning/YARN discovery are the pod scheduler's
job now (GKE/xmanager); what remains launcher-shaped is: initialize the
distributed runtime, build the mesh, start host-side services on process
0, boot the workflow, and shut everything down."""

import os

from veles_tpu import telemetry
from veles_tpu.logger import Logger
from veles_tpu.parallel import MeshConfig, make_mesh


def filter_argv(argv, *flags):
    """Drop ``flags`` from an argv list — used when respawning/forwarding
    commands (ref launcher.py:75).  A flag spelled with a trailing ``=``
    (e.g. ``"-l="``) also consumes its separate value argument; a bare
    flag name drops only the flag itself (boolean switches)."""
    value_flags = {f[:-1] for f in flags if f.endswith("=")}
    bare_flags = {f for f in flags if not f.endswith("=")}
    out = []
    skip = False
    for arg in argv:
        if skip:
            skip = False
            continue
        key = arg.split("=", 1)[0]
        if key in value_flags:
            skip = "=" not in arg
            continue
        if key in bare_flags:
            continue
        out.append(arg)
    return out


class Launcher(Logger):
    def __init__(self, workflow=None, mode=None, coordinator_address=None,
                 num_processes=None, process_id=None, mesh_axes=None,
                 web_status_port=None, graphics_endpoint=None, fsdp=False,
                 **kwargs):
        super(Launcher, self).__init__(**kwargs)
        self.workflow = workflow
        self.coordinator_address = (coordinator_address or
                                    os.environ.get("VELES_TPU_COORDINATOR"))
        self.num_processes = num_processes or int(
            os.environ.get("VELES_TPU_NUM_PROCESSES", "1"))
        self.process_id = (process_id if process_id is not None else
                           int(os.environ.get("VELES_TPU_PROCESS_ID", "0")))
        if mode is None:
            mode = ("spmd" if (self.coordinator_address or
                               self.num_processes > 1) else "standalone")
        self.mode = mode
        self.mesh_axes = mesh_axes
        self.fsdp = fsdp
        self.mesh_config = None
        self.web_status_port = web_status_port
        self.graphics_endpoint = graphics_endpoint
        self.web_server = None
        self.graphics_server = None
        self._initialized = False

    # ------------------------------------------------------------ identity
    @property
    def is_standalone(self):
        return self.mode == "standalone"

    @property
    def is_master(self):
        """Process 0 owns snapshots/dashboards (ref master duties)."""
        import jax
        return jax.process_index() == 0

    @property
    def is_slave(self):
        return not self.is_master

    # ----------------------------------------------------------- lifecycle
    def initialize(self, **kwargs):
        import jax
        if self.mode == "spmd" and self.num_processes > 1:
            from veles_tpu.compile_cache import _cpu_backend
            if _cpu_backend():
                # multi-process SPMD on the CPU backend (emulated pods,
                # tests, the pod-chaos gate) needs an explicit CPU
                # collectives implementation — without it every
                # cross-process collective dies with "Multiprocess
                # computations aren't implemented on the CPU backend".
                # Must land before the backend initializes; harmless to
                # set again on re-entry, no-op for TPU/GPU platforms.
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except (AttributeError, ValueError):
                    pass   # older jax: no such knob (or no gloo build)
            self.info("jax.distributed.initialize(%s, %d, %d)",
                      self.coordinator_address, self.num_processes,
                      self.process_id)
            jax.distributed.initialize(
                coordinator_address=self.coordinator_address,
                num_processes=self.num_processes,
                process_id=self.process_id)
        if self.mesh_axes:
            axes = self.mesh_axes
            from veles_tpu.config import root
            if root.common.pod.get("elastic_mesh", False) or \
                    os.environ.get("VELES_TPU_ELASTIC_MESH") == "1":
                # elastic pods (services.podmaster) respawn workers on
                # whatever hosts survive: the mesh must be built from
                # the LIVE device set, not the configured topology — a
                # fixed data axis rescales, model/seq/... axes must fit
                from veles_tpu.parallel.mesh import fit_axes_to_devices
                import jax as _jax
                fitted = fit_axes_to_devices(axes,
                                             _jax.device_count())
                if fitted != dict(axes):
                    self.info("elastic mesh: %s -> %s (%d live "
                              "devices)", dict(axes), fitted,
                              _jax.device_count())
                    telemetry.flight.record(
                        "mesh.refit", configured=dict(axes),
                        live=fitted, devices=_jax.device_count())
                    # kernel-autotuner winners are keyed by mesh
                    # topology: the configured (full-size) entries are
                    # invalidated so the degraded pod RE-TUNES for its
                    # survivor mesh instead of inheriting block sizes
                    # measured at full size (docs/perf.md "Autotuning")
                    try:
                        from veles_tpu import tuner as _tuner
                        _tuner.on_mesh_refit(dict(axes), fitted)
                    except Exception:  # noqa: BLE001 — advisory
                        pass
                axes = fitted
            try:
                from veles_tpu import tuner as _tuner
                _tuner.set_ambient_mesh(axes)
            except Exception:  # noqa: BLE001 — advisory
                pass
            self.mesh_config = MeshConfig(make_mesh(axes),
                                          fsdp=self.fsdp)
            if self.fsdp and self.mesh_config.data_size <= 1:
                self.warning("--fsdp has no effect: the mesh has no "
                             "data axis larger than 1 (got %s)",
                             dict(self.mesh_config.mesh.shape))
        elif self.fsdp:
            self.warning("--fsdp ignored: no --mesh given (parameters "
                         "shard over the mesh's data axis)")
        if jax.process_count() > 1 and self.workflow is not None:
            self._verify_checksum()
        if self.is_master:
            self._launch_services()
        # services are live from here on: a failure anywhere below
        # (crash-handler install, workflow.initialize raising, even the
        # telemetry gauge) must tear them down before re-raising, or the
        # web-status/graphics daemon threads leak — boot() and the CLI
        # rely on this rather than wrapping initialize() themselves
        try:
            self._install_blackbox()
            if self.workflow is not None:
                trainer = getattr(self.workflow, "trainer", None)
                # only trainers that understand meshes (StagedTrainer) —
                # Kohonen/RBM trainers have no mesh_config attribute
                if self.mesh_config is not None and trainer is not None:
                    if not hasattr(trainer, "mesh_config"):
                        self.warning("--mesh ignored: %s does not support "
                                     "SPMD meshes", type(trainer).__name__)
                    elif trainer.mesh_config is None:
                        trainer.mesh_config = self.mesh_config
                # the trainer will row-shard the dataset: the loader must
                # not materialize a single-device replica first (the
                # workflow constructor handles this when it got
                # mesh_config directly; this covers the --mesh CLI path
                # where the mesh is assigned here, before any unit
                # initializes)
                mc = getattr(trainer, "mesh_config", None)
                loader = getattr(self.workflow, "loader", None)
                if (mc is not None and loader is not None
                        and getattr(trainer, "dataset_placement", None)
                        == "shard" and mc.data_size > 1
                        and getattr(loader, "on_device", None) is True):
                    loader.on_device = "defer"
                # initialization is where first-compiles land: span it so
                # the metrics JSONL attributes that wall time correctly
                # (and the TraceAnnotation names it in a device capture)
                with telemetry.span("workflow.initialize", emit=True,
                                    workflow=self.workflow.name):
                    self.workflow.initialize(**kwargs)
            telemetry.registry.gauge(
                "veles_launcher_info",
                "constant 1; run topology rides the labels",
                ("mode", "processes")).set(
                1, mode=self.mode, processes=self.num_processes)
            # the watchdog arms only AFTER initialize returns: its
            # progress clock must not start against first compiles,
            # which routinely exceed any sane hang window (runtime
            # recompiles keep feeding it via the compile listeners)
            self._arm_health()
        except BaseException as e:
            telemetry.flight.record(
                "launcher.initialize_failed",
                error=type(e).__name__, message=str(e))
            self.stop()
            raise
        self._initialized = True

    def _install_blackbox(self):
        """Install the crash-forensics hooks (telemetry.health) — live
        BEFORE workflow.initialize so a crash during initialization
        still leaves a black box.  The watchdog/heartbeat arm later
        (`_arm_health`), once initialization's first compiles are paid;
        tests and plain standalone runs pay nothing (docs/services.md
        "Black box")."""
        from veles_tpu.telemetry import health
        health.install(mode=self.mode, workflow=self.workflow)

    def _arm_health(self):
        """Arm the hang watchdog and the multi-host heartbeat/desync
        check: spmd runs by default, standalone only when the config
        asks explicitly."""
        from veles_tpu.config import root
        from veles_tpu.telemetry import health
        # None = unset; an EXPLICIT watchdog_seconds=0 (--watchdog 0)
        # disarms even spmd, where unset defaults to the spmd window
        window = root.common.blackbox.get("watchdog_seconds", None)
        if window is None and self.mode == "spmd":
            window = root.common.blackbox.get("spmd_watchdog_seconds",
                                              300)
        if window:
            health.arm_watchdog(window)
        if self.mode == "spmd" and self.num_processes > 1:
            health.enable_multihost()

    def _verify_checksum(self):
        """Every process must run the same workflow code (ref the per-file
        SHA1 handshake check, veles/workflow.py:847 + server.py:478) —
        a silently divergent binary would produce corrupt collectives."""
        import numpy as np
        from jax.experimental import multihost_utils
        digest = np.frombuffer(
            bytes.fromhex(self.workflow.checksum()), np.uint8)
        gathered = np.asarray(multihost_utils.process_allgather(digest))
        if not (gathered == digest[None, :]).all():
            raise RuntimeError(
                "workflow checksum mismatch across processes — every "
                "host must run identical workflow code")
        self.debug("workflow checksum verified across %d processes",
                   gathered.shape[0] if gathered.ndim > 1 else 1)

    def _launch_services(self):
        if self.web_status_port is not None:
            from veles_tpu.services.web_status import WebStatusServer
            self.web_server = WebStatusServer(port=self.web_status_port)
            if self.workflow is not None:
                self.web_server.register(self.workflow)
            self.web_server.start()
        if self.graphics_endpoint is not None:
            from veles_tpu.services.graphics import GraphicsServer
            self.graphics_server = GraphicsServer(
                endpoint=self.graphics_endpoint).start()

    def run(self):
        if not self._initialized:
            raise RuntimeError("Launcher.run() before initialize()")
        try:
            self.workflow.run()
        finally:
            self.stop()

    def boot(self, **kwargs):
        """initialize() + run() (ref launcher.py:573)."""
        self.initialize(**kwargs)
        self.run()

    def stop(self):
        """Idempotent — run() calls it in its finally and the CLI calls it
        again on the way out."""
        from veles_tpu.telemetry import health
        health.disarm_watchdog()
        if self.graphics_server is not None:
            self.graphics_server.stop()
            self.graphics_server = None
        if self.web_server is not None:
            self.web_server.stop()
            self.web_server = None
