"""Distributed execution (ref: veles/server.py, client.py, launcher.py —
SURVEY.md §2.6).

The reference's master–slave star (Twisted TCP control + ZeroMQ pickled
gradient deltas, async parameter-server SGD over ≤100 nodes) is replaced by
SPMD over a ``jax.sharding.Mesh``: the gradient exchange is a ``psum`` XLA
inserts over ICI when the batch axis is sharded; the control plane is
``jax.distributed`` over DCN for multi-host.  The reference's SharedIO shm,
pickle compression, computing-power balancing, and elastic join all
dissolve: arrays are HBM-resident, the pod is homogeneous, and elasticity
is checkpoint-restart *resized to the survivors*: the pod master
(services.podmaster) rebuilds the mesh from the live host set
(:func:`mesh.fit_axes_to_devices`) and the snapshotter reshards the
topology-tagged checkpoint onto it (snapshotter.reshard_state), so a
permanently lost host degrades the pod instead of ending it."""

from veles_tpu.parallel.mesh import (MeshConfig, fit_axes_to_devices,
                                     make_mesh)
from veles_tpu.parallel import sharding

__all__ = ["MeshConfig", "fit_axes_to_devices", "make_mesh", "sharding"]
