"""Sharding rules: which mesh axes each tensor rides
(the "pick a mesh, annotate shardings, let XLA insert collectives" recipe).

Parameter rule (tensor parallelism): dense weights (in, out) shard their
*output* dimension over the model axis (Megatron column-parallel); conv
kernels (kh, kw, cin, cout) shard output channels; biases follow their
weights.  Activations are left to GSPMD propagation.  Anything whose dim
doesn't divide the axis stays replicated — correctness never depends on
divisibility.

Data rule (data parallelism): the minibatch index/valid vectors shard over
the data axis; the HBM-resident dataset and labels are replicated (each
shard gathers its own rows).  With params replicated on the data axis and
batch sharded, XLA inserts the gradient ``psum`` over ICI — the TPU-native
equivalent of the reference's master-apply of slave gradient deltas
(veles/workflow.py:529 apply_data_from_slave)."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def param_spec(shape, mesh_cfg):
    """PartitionSpec for one parameter tensor under the model axis."""
    axis = mesh_cfg.model_axis
    size = mesh_cfg.model_size
    if size <= 1 or not shape:
        return P()
    out_dim = len(shape) - 1
    if shape[out_dim] % size == 0:
        spec = [None] * len(shape)
        spec[out_dim] = axis
        return P(*spec)
    return P()


def shard_params(params, mesh_cfg):
    """device_put a {layer: {name: array}} pytree with model-axis sharding."""
    mesh = mesh_cfg.mesh

    def place(x):
        return jax.device_put(
            x, NamedSharding(mesh, param_spec(x.shape, mesh_cfg)))

    return jax.tree_util.tree_map(place, params)


def param_shardings(params, mesh_cfg):
    mesh = mesh_cfg.mesh
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, param_spec(x.shape, mesh_cfg)), params)


def replicate(x, mesh_cfg):
    return jax.device_put(x, NamedSharding(mesh_cfg.mesh, P()))


def shard_batch(x, mesh_cfg):
    """Shard the leading (minibatch) dim over the data axis."""
    return jax.device_put(
        x, NamedSharding(mesh_cfg.mesh, P(mesh_cfg.data_axis)))


def replicated_sharding(mesh_cfg):
    return NamedSharding(mesh_cfg.mesh, P())
