"""Sharding rules: which mesh axes each tensor rides
(the "pick a mesh, annotate shardings, let XLA insert collectives" recipe).

Parameter rule (tensor parallelism): dense weights (in, out) shard their
*output* dimension over the model axis (Megatron column-parallel); conv
kernels (kh, kw, cin, cout) shard output channels; biases follow their
weights.  Activations are left to GSPMD propagation.  Anything whose dim
doesn't divide the axis stays replicated — correctness never depends on
divisibility.

Data rule (data parallelism): the minibatch index/valid vectors shard over
the data axis; the HBM-resident dataset and labels are replicated (each
shard gathers its own rows).  With params replicated on the data axis and
batch sharded, XLA inserts the gradient ``psum`` over ICI — the TPU-native
equivalent of the reference's master-apply of slave gradient deltas
(veles/workflow.py:529 apply_data_from_slave).

FSDP rule (``MeshConfig(fsdp=True)`` / ``--fsdp``): parameters (and
their optimizer state) additionally shard their FIRST dim over the data
axis where it divides — ZeRO-3: 1/D of the model per worker, GSPMD
inserts the all-gather before use and a reduce-scatter (not psum) on
the gradients."""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


#: optimizer-state slot names (models/optimizer.init_state): a fallback
#: on ``slot1/l03_dense/weights`` is the SAME fallback as on
#: ``l03_dense/weights`` — strip the slot so the record (and VS201)
#: reports the layer once
_SLOT_KEYS = ("slot1", "slot2", "gacc", "ema")


def _layer_param(path):
    parts = list(path)
    if parts and parts[0] in _SLOT_KEYS:
        parts = parts[1:]
    layer = parts[0] if parts else None
    param = ".".join(parts[1:]) if len(parts) > 1 else None
    return layer, param


def _record(mesh_cfg, path, dim, axis, reason, shape, replicated=True):
    """Log a sharding fallback on the mesh config (VS201 feed);
    tolerant of bare MeshConfig-likes without the recorder.
    ``replicated=False``: the tensor kept a sharding on another axis
    and only missed this one (informational, not a silent replica)."""
    rec = getattr(mesh_cfg, "record_fallback", None)
    if rec is not None:
        layer, param = _layer_param(path or ())
        rec(layer, param, dim, axis, reason, shape,
            replicated=replicated)


def param_spec(shape, mesh_cfg, path=()):
    """PartitionSpec for one parameter tensor: model axis on the output
    (last) dim — Megatron column parallelism — and, when the mesh config
    asks for ``fsdp``, the data axis on the first dim (ZeRO-3-style fully
    sharded params: each data-parallel worker stores 1/D of every weight
    and its optimizer state; GSPMD inserts the all-gather before use and
    the reduce-scatter on the gradient).  Dims that don't divide stay
    replicated — correctness never depends on divisibility — but every
    such fallback is RECORDED on ``mesh_cfg.sharding_fallbacks`` (keyed
    by ``path``, the layer/param names) so the VS201 lint rule can report
    which layer silently lost its sharding and why."""
    if not shape:
        return P()
    spec = [None] * len(shape)
    m_size = mesh_cfg.model_size
    if m_size > 1:
        if shape[-1] % m_size == 0:
            spec[-1] = mesh_cfg.model_axis
        else:
            _record(mesh_cfg, path, len(shape) - 1, mesh_cfg.model_axis,
                    "output dim %d not divisible by %s=%d — tensor "
                    "stays replicated over the model axis"
                    % (shape[-1], mesh_cfg.model_axis, m_size), shape)
    d_size = mesh_cfg.data_size
    if getattr(mesh_cfg, "fsdp", False) and d_size > 1:
        if spec[0] is not None:
            # still model-axis sharded — an informational miss of the
            # EXTRA fsdp axis, not a silent replication (every 1-D bias
            # hits this on every fsdp mesh)
            _record(mesh_cfg, path, 0, mesh_cfg.data_axis,
                    "fsdp skip: dim 0 already carries the model axis — "
                    "parameter is NOT additionally sharded over %s=%d"
                    % (mesh_cfg.data_axis, d_size), shape,
                    replicated=False)
        elif shape[0] % d_size == 0:
            spec[0] = mesh_cfg.data_axis
        else:
            _record(mesh_cfg, path, 0, mesh_cfg.data_axis,
                    "fsdp: dim %d not divisible by %s=%d — parameter "
                    "(and its optimizer state) stays replicated over "
                    "the data axis" % (shape[0], mesh_cfg.data_axis,
                                       d_size), shape)
    while spec and spec[-1] is None:    # canonical: no trailing Nones
        spec.pop()
    return P(*spec)


def _safe_spec(shape, spec, mesh_cfg, path=()):
    """Keep an override spec only where the named dims divide evenly;
    otherwise replicate (correctness never depends on divisibility) and
    record the fallback for VS201."""
    if spec is None:
        return param_spec(shape, mesh_cfg, path)
    entries = tuple(spec)
    if len(entries) > len(shape):
        _record(mesh_cfg, path, None, None,
                "override spec %s names %d dims but the tensor has "
                "only %d — whole tensor replicated"
                % (spec, len(entries), len(shape)), shape)
        return P()
    for dim, axis in enumerate(entries):
        if axis is None:
            continue
        size = mesh_cfg.mesh.shape.get(axis, 1)
        if size > 1 and shape[dim] % size:
            _record(mesh_cfg, path, dim, axis,
                    "override dim %d (size %d) not divisible by "
                    "%s=%d — whole tensor replicated"
                    % (dim, shape[dim], axis, size), shape)
            return P()
    return spec


def _walk_leaves(tree, fn, path=()):
    """tree_map with the dict-key path handed to ``fn(leaf, path)`` —
    param trees are nested dicts, so manual recursion suffices."""
    if isinstance(tree, dict):
        return {k: _walk_leaves(v, fn, path + (k,))
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(_walk_leaves(v, fn, path + (str(i),))
                          for i, v in enumerate(tree))
    return fn(tree, path)


def _specs_tree(tree, overrides, mesh_cfg, path=()):
    """Spec pytree for ``tree``.  ``overrides`` maps a dict key (layer
    name, at any nesting level — the velocity tree nests layers under
    slot names) to either a PartitionSpec applied to every leaf below it,
    or a partial dict mirroring the subtree (missing keys fall back to
    the default model-axis rule)."""
    def apply_override(sub, ov, p):
        if isinstance(ov, dict):
            if not isinstance(sub, dict):
                raise TypeError("override dict against non-dict params")
            return {k: (apply_override(v, ov[k], p + (k,)) if k in ov
                        and ov[k] is not None
                        else _specs_tree(v, overrides, mesh_cfg, p + (k,)))
                    for k, v in sub.items()}
        return _walk_leaves(
            sub, lambda x, lp: _safe_spec(x.shape, ov, mesh_cfg, lp), p)

    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            ov = (overrides or {}).get(k)
            out[k] = (apply_override(v, ov, path + (k,))
                      if ov is not None
                      else _specs_tree(v, overrides, mesh_cfg,
                                       path + (k,)))
        return out
    return param_spec(tree.shape, mesh_cfg, path)


def shard_params(params, mesh_cfg, overrides=None):
    """device_put a {layer: {name: array}} pytree.  Default rule:
    model-axis tensor parallelism; ``overrides`` (from
    Layer.param_partition_specs) shard e.g. expert banks over 'expert'
    and pipeline stages over 'pipe'."""
    mesh = mesh_cfg.mesh
    specs = _specs_tree(params, overrides, mesh_cfg)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)


def param_shardings(params, mesh_cfg, overrides=None):
    mesh = mesh_cfg.mesh
    specs = _specs_tree(params, overrides, mesh_cfg)
    return jax.tree_util.tree_map(
        lambda x, s: NamedSharding(mesh, s), params, specs)


def replicate(x, mesh_cfg):
    return jax.device_put(x, NamedSharding(mesh_cfg.mesh, P()))


def shard_dataset(x, mesh_cfg):
    """Place a whole dataset with its sample dim sharded over the data
    axis — each device holds 1/data_size of the rows instead of a full
    replica (lifts the r1 replication that made ImageNet-scale fullbatch
    impossible; ref OOM concern veles/loader/fullbatch.py:164-242).
    Rows are zero-padded up to a multiple of the axis size; padding rows
    are never referenced (indices < true length)."""
    import numpy as np
    d = mesh_cfg.data_size
    n = x.shape[0]
    pad = (-n) % d
    if pad:
        x = np.concatenate(
            [np.asarray(x),
             np.zeros((pad,) + tuple(x.shape[1:]), np.asarray(x).dtype)])
    return jax.device_put(
        x, NamedSharding(mesh_cfg.mesh, P(mesh_cfg.data_axis)))


def make_sharded_gather(mesh_cfg):
    """Minibatch gather against a row-sharded dataset, for use INSIDE the
    jitted step.  Each device: all_gathers the (tiny, int32) index vector,
    gathers the rows it owns locally (others masked to 0), then a
    ``psum_scatter`` over the data axis both completes every row and hands
    each device exactly its own 1/D slice of the minibatch — total ICI
    traffic is one minibatch, never the dataset.  (TPU-native equivalent
    of the reference's fill_minibatch_data_labels gather,
    ocl/fullbatch_loader.cl, against a dataset no single device holds.)"""
    from veles_tpu.parallel.smap import shard_map

    axis = mesh_cfg.data_axis
    mesh = mesh_cfg.mesh

    def local(data_local, idx_local):
        rows_per = data_local.shape[0]
        idx_all = jax.lax.all_gather(idx_local, axis, tiled=True)   # [B]
        loc = jnp.maximum(idx_all, 0) - jax.lax.axis_index(axis) * rows_per
        ok = (loc >= 0) & (loc < rows_per)
        part = jnp.take(data_local, jnp.clip(loc, 0, rows_per - 1), axis=0)
        mask = ok.reshape((ok.shape[0],) + (1,) * (part.ndim - 1))
        part = jnp.where(mask, part, jnp.zeros((), part.dtype))
        return jax.lax.psum_scatter(part, axis, scatter_dimension=0,
                                    tiled=True)

    return shard_map(local, mesh=mesh, in_specs=(P(axis), P(axis)),
                     out_specs=P(axis))


def shard_batch(x, mesh_cfg):
    """Shard the leading (minibatch) dim over the data axis (replicated
    when the mesh has no data axis — e.g. a pure tensor-parallel mesh)."""
    if mesh_cfg.data_axis not in mesh_cfg.mesh.shape:
        return replicate(x, mesh_cfg)
    return jax.device_put(
        x, NamedSharding(mesh_cfg.mesh, P(mesh_cfg.data_axis)))


def shard_batch_stack(x, mesh_cfg):
    """Shard dim 1 (minibatch) of a [k, B, ...] stack over the data axis —
    the fused k-step sweep's index/valid matrices, one transfer per k
    steps."""
    if mesh_cfg.data_axis not in mesh_cfg.mesh.shape:
        return replicate(x, mesh_cfg)
    return jax.device_put(
        x, NamedSharding(mesh_cfg.mesh,
                         P(None, mesh_cfg.data_axis)))


def replicated_sharding(mesh_cfg):
    return NamedSharding(mesh_cfg.mesh, P())
