"""Sharding rules: which mesh axes each tensor rides
(the "pick a mesh, annotate shardings, let XLA insert collectives" recipe).

Parameter rule (tensor parallelism): dense weights (in, out) shard their
*output* dimension over the model axis (Megatron column-parallel); conv
kernels (kh, kw, cin, cout) shard output channels; biases follow their
weights.  Activations are left to GSPMD propagation.  Anything whose dim
doesn't divide the axis stays replicated — correctness never depends on
divisibility.

Data rule (data parallelism): the minibatch index/valid vectors shard over
the data axis; the HBM-resident dataset and labels are replicated (each
shard gathers its own rows).  With params replicated on the data axis and
batch sharded, XLA inserts the gradient ``psum`` over ICI — the TPU-native
equivalent of the reference's master-apply of slave gradient deltas
(veles/workflow.py:529 apply_data_from_slave)."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def param_spec(shape, mesh_cfg):
    """PartitionSpec for one parameter tensor under the model axis."""
    axis = mesh_cfg.model_axis
    size = mesh_cfg.model_size
    if size <= 1 or not shape:
        return P()
    out_dim = len(shape) - 1
    if shape[out_dim] % size == 0:
        spec = [None] * len(shape)
        spec[out_dim] = axis
        return P(*spec)
    return P()


def _safe_spec(shape, spec, mesh_cfg):
    """Keep an override spec only where the named dims divide evenly;
    otherwise replicate (correctness never depends on divisibility)."""
    if spec is None:
        return param_spec(shape, mesh_cfg)
    entries = tuple(spec)
    if len(entries) > len(shape):
        return P()
    for dim, axis in enumerate(entries):
        if axis is None:
            continue
        size = mesh_cfg.mesh.shape.get(axis, 1)
        if size > 1 and shape[dim] % size:
            return P()
    return spec


def _specs_tree(tree, overrides, mesh_cfg):
    """Spec pytree for ``tree``.  ``overrides`` maps a dict key (layer
    name, at any nesting level — the velocity tree nests layers under
    slot names) to either a PartitionSpec applied to every leaf below it,
    or a partial dict mirroring the subtree (missing keys fall back to
    the default model-axis rule)."""
    def apply_override(sub, ov):
        if isinstance(ov, P):
            return jax.tree_util.tree_map(
                lambda x: _safe_spec(x.shape, ov, mesh_cfg), sub)
        if isinstance(ov, dict):
            if not isinstance(sub, dict):
                raise TypeError("override dict against non-dict params")
            return {k: (apply_override(v, ov[k]) if k in ov
                        and ov[k] is not None
                        else _specs_tree(v, overrides, mesh_cfg))
                    for k, v in sub.items()}
        return jax.tree_util.tree_map(
            lambda x: _safe_spec(x.shape, ov, mesh_cfg), sub)

    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            ov = (overrides or {}).get(k)
            out[k] = (apply_override(v, ov) if ov is not None
                      else _specs_tree(v, overrides, mesh_cfg))
        return out
    return param_spec(tree.shape, mesh_cfg)


def shard_params(params, mesh_cfg, overrides=None):
    """device_put a {layer: {name: array}} pytree.  Default rule:
    model-axis tensor parallelism; ``overrides`` (from
    Layer.param_partition_specs) shard e.g. expert banks over 'expert'
    and pipeline stages over 'pipe'."""
    mesh = mesh_cfg.mesh
    specs = _specs_tree(params, overrides, mesh_cfg)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)


def param_shardings(params, mesh_cfg, overrides=None):
    mesh = mesh_cfg.mesh
    specs = _specs_tree(params, overrides, mesh_cfg)
    return jax.tree_util.tree_map(
        lambda x, s: NamedSharding(mesh, s), params, specs)


def replicate(x, mesh_cfg):
    return jax.device_put(x, NamedSharding(mesh_cfg.mesh, P()))


def shard_batch(x, mesh_cfg):
    """Shard the leading (minibatch) dim over the data axis."""
    return jax.device_put(
        x, NamedSharding(mesh_cfg.mesh, P(mesh_cfg.data_axis)))


def replicated_sharding(mesh_cfg):
    return NamedSharding(mesh_cfg.mesh, P())
