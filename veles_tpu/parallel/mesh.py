"""Mesh construction — the TPU replacement for the reference's device
selection (`veles/backends.py` Device.__new__ backend dispatch) and the
launcher's node specs (`launcher.py` -n host/0:0x3 grammar).

A MeshConfig names the axes of a ``jax.sharding.Mesh``:
  * ``data``  — batch/data parallelism (gradient psum over ICI)
  * ``model`` — tensor parallelism (dense/conv output-channel sharding)
Multi-host: call ``jax.distributed.initialize`` first; ``jax.devices()``
then spans the pod and the same mesh code works unchanged."""

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axes=None, devices=None):
    """Build a Mesh from {axis_name: size}.  A size of -1 absorbs all
    remaining devices.  Default: all devices on one ``data`` axis."""
    devices = list(devices if devices is not None else jax.devices())
    if not axes:
        axes = {"data": len(devices)}
    axes = dict(axes)
    names = list(axes)
    known = 1
    wild = None
    for name, size in axes.items():
        if size == -1:
            if wild is not None:
                raise ValueError("only one axis may be -1")
            wild = name
        else:
            known *= int(size)
    if wild is not None:
        if len(devices) % known:
            raise ValueError("cannot infer %r: %d devices not divisible "
                             "by %d" % (wild, len(devices), known))
        axes[wild] = len(devices) // known
        known *= axes[wild]
    if known > len(devices):
        raise ValueError("mesh wants %d devices, have %d"
                         % (known, len(devices)))
    devs = np.asarray(devices[:known]).reshape(
        [axes[n] for n in names])
    return Mesh(devs, tuple(names))


@dataclasses.dataclass
class MeshConfig:
    """Axis naming convention shared by trainer/loader/sharding rules.

    ``fsdp=True`` fully shards parameters (and optimizer state) over the
    data axis in addition to data-parallel batches — ZeRO-3-style: 1/D
    of every weight per worker, all-gather on use, reduce-scatter on
    gradients, all inserted by GSPMD from the sharding annotations."""

    mesh: Mesh
    data_axis: str = "data"
    model_axis: str = "model"
    fsdp: bool = False
    #: silent-replication log: every place the sharding rules *wanted* to
    #: shard a tensor but fell back to replication records
    #: ``{layer, param, dim, axis, reason, shape}`` here (sharding.py
    #: param_spec/_safe_spec) — the VS201 lint rule reports these instead
    #: of letting a non-dividing dim silently cost a full replica per
    #: device.  Deduplicated; params and their optimizer slots collapse
    #: to one entry.
    sharding_fallbacks: list = dataclasses.field(default_factory=list,
                                                 repr=False, compare=False)

    def record_fallback(self, layer, param, dim, axis, reason,
                        shape=None, replicated=True):
        """``replicated=False`` marks a tensor that merely missed ONE
        extra axis (e.g. a model-sharded bias fsdp could not also
        shard) — still sharded, reported informationally, not as a
        silent replication."""
        entry = {"layer": layer, "param": param, "dim": dim, "axis": axis,
                 "reason": reason, "replicated": bool(replicated),
                 "shape": tuple(shape) if shape is not None else None}
        if entry not in self.sharding_fallbacks:
            self.sharding_fallbacks.append(entry)

    def clear_fallbacks(self):
        del self.sharding_fallbacks[:]

    @property
    def data_size(self):
        return (self.mesh.shape[self.data_axis]
                if self.data_axis in self.mesh.shape else 1)

    @property
    def model_size(self):
        return (self.mesh.shape[self.model_axis]
                if self.model_axis in self.mesh.shape else 1)
