"""Mesh construction — the TPU replacement for the reference's device
selection (`veles/backends.py` Device.__new__ backend dispatch) and the
launcher's node specs (`launcher.py` -n host/0:0x3 grammar).

A MeshConfig names the axes of a ``jax.sharding.Mesh``:
  * ``data``  — batch/data parallelism (gradient psum over ICI)
  * ``model`` — tensor parallelism (dense/conv output-channel sharding)
Multi-host: call ``jax.distributed.initialize`` first; ``jax.devices()``
then spans the pod and the same mesh code works unchanged."""

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axes=None, devices=None):
    """Build a Mesh from {axis_name: size}.  A size of -1 absorbs all
    remaining devices.  Default: all devices on one ``data`` axis."""
    devices = list(devices if devices is not None else jax.devices())
    if not axes:
        axes = {"data": len(devices)}
    axes = dict(axes)
    names = list(axes)
    known = 1
    wild = None
    for name, size in axes.items():
        if size == -1:
            if wild is not None:
                raise ValueError("only one axis may be -1")
            wild = name
        else:
            known *= int(size)
    if wild is not None:
        if len(devices) % known:
            raise ValueError("cannot infer %r: %d devices not divisible "
                             "by %d" % (wild, len(devices), known))
        axes[wild] = len(devices) // known
        known *= axes[wild]
    if known > len(devices):
        raise ValueError("mesh wants %d devices, have %d"
                         % (known, len(devices)))
    devs = np.asarray(devices[:known]).reshape(
        [axes[n] for n in names])
    return Mesh(devs, tuple(names))


def fit_axes_to_devices(axes, n_devices, data_axis="data"):
    """Rescale a configured ``{axis: size}`` spec onto the devices that
    are actually alive — the elastic-pod path (services.podmaster): a
    mesh configured for 4 hosts must rebuild on the 3 survivors instead
    of retrying the dead topology forever.

    Only the **data** axis resizes (including a ``data=-1`` wildcard,
    which already adapts): data parallelism is the axis
    checkpoint-restart can legally shrink/grow — the global minibatch is
    resharded, the math is the same psum over fewer partials.
    Model/seq/expert/pipe axes are woven into the parameters' layout and
    must survive intact: when the live device count cannot hold them,
    that is an error, not a silent re-layout — and a ``-1`` wildcard on
    a NON-data axis is refused outright, because ``make_mesh`` would
    resolve it against whatever device count is alive, silently
    re-laying the model out at each pod size.

    Returns a NEW axes dict whose product fits ``n_devices`` exactly
    (for the non-wildcard case), or the input unchanged when it already
    fits.  Raises ValueError when no legal resize exists."""
    axes = dict(axes) if axes else {data_axis: -1}
    n_devices = int(n_devices)
    wild_nondata = sorted(name for name, size in axes.items()
                          if size == -1 and name != data_axis)
    if wild_nondata:
        raise ValueError(
            "mesh %r cannot be refitted: the -1 wildcard on non-data "
            "axis(es) %s would resolve against the LIVE device count "
            "and silently re-lay the model out at each pod size — pin "
            "those axes (elastic pods rescale the data axis only, or "
            "a data=-1 wildcard)" % (axes, wild_nondata))
    fixed = 1
    for name, size in axes.items():
        if name != data_axis and size != -1:
            fixed *= int(size)
    if axes.get(data_axis) == -1:
        # a data wildcard already absorbs whatever is alive — validate
        # the fixed axes still fit and let make_mesh do the division
        if n_devices % fixed:
            raise ValueError(
                "mesh %r cannot fit %d live devices: the fixed axes "
                "need a multiple of %d" % (axes, n_devices, fixed))
        return axes
    want = fixed * int(axes.get(data_axis, 1))
    if want == n_devices:
        return axes
    if n_devices % fixed:
        raise ValueError(
            "mesh %r cannot resize onto %d live devices: the non-data "
            "axes need a multiple of %d devices (the data axis is the "
            "only one elasticity may rescale)" % (axes, n_devices, fixed))
    out = dict(axes)
    out[data_axis] = n_devices // fixed
    return out


@dataclasses.dataclass
class MeshConfig:
    """Axis naming convention shared by trainer/loader/sharding rules.

    ``fsdp=True`` fully shards parameters (and optimizer state) over the
    data axis in addition to data-parallel batches — ZeRO-3-style: 1/D
    of every weight per worker, all-gather on use, reduce-scatter on
    gradients, all inserted by GSPMD from the sharding annotations."""

    mesh: Mesh
    data_axis: str = "data"
    model_axis: str = "model"
    fsdp: bool = False
    #: silent-replication log: every place the sharding rules *wanted* to
    #: shard a tensor but fell back to replication records
    #: ``{layer, param, dim, axis, reason, shape}`` here (sharding.py
    #: param_spec/_safe_spec) — the VS201 lint rule reports these instead
    #: of letting a non-dividing dim silently cost a full replica per
    #: device.  Deduplicated; params and their optimizer slots collapse
    #: to one entry.
    sharding_fallbacks: list = dataclasses.field(default_factory=list,
                                                 repr=False, compare=False)

    def record_fallback(self, layer, param, dim, axis, reason,
                        shape=None, replicated=True):
        """``replicated=False`` marks a tensor that merely missed ONE
        extra axis (e.g. a model-sharded bias fsdp could not also
        shard) — still sharded, reported informationally, not as a
        silent replication."""
        entry = {"layer": layer, "param": param, "dim": dim, "axis": axis,
                 "reason": reason, "replicated": bool(replicated),
                 "shape": tuple(shape) if shape is not None else None}
        if entry not in self.sharding_fallbacks:
            self.sharding_fallbacks.append(entry)

    def clear_fallbacks(self):
        del self.sharding_fallbacks[:]

    @property
    def data_size(self):
        return (self.mesh.shape[self.data_axis]
                if self.data_axis in self.mesh.shape else 1)

    @property
    def model_size(self):
        return (self.mesh.shape[self.model_axis]
                if self.model_axis in self.mesh.shape else 1)
