"""Pipeline parallelism: GPipe-style microbatch pipelining over a
``pipe`` mesh axis.

New capability beyond the reference (SURVEY.md §2.6: PP absent there).
Each device holds ONE stage's parameters; activations flow stage-to-stage
with ``lax.ppermute`` (one ICI neighbor hop per tick) while microbatches
stream through, so all stages compute concurrently after the fill phase —
the classic GPipe schedule with bubble fraction (S-1)/(M+S-1).

GPipe constraint: every stage maps activations to the SAME shape (the
transformer-block regime pipelining is used for); embed/head layers live
outside the pipelined segment.  The whole schedule is a ``lax.scan``, so
it jits, differentiates (reverse-mode re-runs the scan), and composes
with the other mesh axes.

``pipeline_train_1f1b`` lifts both GPipe limits for training: the 1F1B
schedule (steady state: one forward + one backward sub-tick per tick)
keeps only O(S) stashed microbatch inputs per device instead of the
O(M) residuals reverse-mode stores through the GPipe scan, and the
first/last stages may differ from the middle ones (``first_fn`` embeds
int tokens, ``last_fn`` runs the head + loss), so embed→blocks→head
pipelines end-to-end.  Backward recomputes each stage forward from the
stashed input (``jax.vjp``) — the same FLOPs-for-memory trade as
full remat, but scheduled so the bubble stays (S-1)/(M+S-1)."""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from veles_tpu.parallel.smap import shard_map


def pipeline_apply(stage_fn, stage_params, x, axis_name, n_microbatches):
    """Inside shard_map over ``axis_name``: run the pipeline.

    stage_fn(stage_params, h) -> h (same shape); ``stage_params`` are THIS
    device's stage weights; ``x`` [B, ...] is the full batch (meaningful on
    stage 0, replicated elsewhere).  Returns [B, ...] outputs of the last
    stage, broadcast to every stage."""
    s = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError("batch %d %% n_microbatches %d != 0"
                         % (b, n_microbatches))
    mb = b // n_microbatches
    xs = x.reshape((n_microbatches, mb) + x.shape[1:])
    n_ticks = n_microbatches + s - 1
    fwd = [(i, i + 1) for i in range(s - 1)]   # no wraparound

    def tick(carry, t):
        outputs, recv = carry
        mb_idx = jnp.clip(t, 0, n_microbatches - 1)
        inp = jnp.where(me == 0, xs[mb_idx], recv)
        out = stage_fn(stage_params, inp)
        # the last stage finishes microbatch t-(s-1) at tick t
        out_idx = jnp.clip(t - (s - 1), 0, n_microbatches - 1)
        write = (me == s - 1) & (t >= s - 1)
        outputs = outputs.at[out_idx].set(
            jnp.where(write, out, outputs[out_idx]))
        recv = lax.ppermute(out, axis_name, fwd)
        return (outputs, recv), None

    outputs = jnp.zeros_like(xs)
    recv0 = jnp.zeros_like(xs[0])
    (outputs, _), _ = lax.scan(tick, (outputs, recv0),
                               jnp.arange(n_ticks))
    # broadcast the last stage's outputs to every device
    y = lax.psum(jnp.where(me == s - 1, outputs, 0.0), axis_name)
    return y.reshape(x.shape)


def pipeline_apply_sharded(stage_fn, stacked_params, x, mesh,
                           pipe_axis="pipe", n_microbatches=4,
                           batch_axis=None):
    """Global entry: ``stacked_params`` has a leading stage axis [S, ...]
    on every leaf, sharded over ``pipe_axis``.  With S == pipe size each
    device keeps one stage; with S == k * pipe size each device keeps k
    consecutive stages and runs them as one scanned "superstage" (fewer
    ICI hops, same math).  jit/grad-composable.

    ``batch_axis``: on a combined {data, pipe} mesh, shard x's batch dim
    over the data axis — each data slice streams ITS OWN microbatches
    through an independent pipeline (the ppermute hops stay within each
    data row of the mesh); without it x replicates and the data axis
    would redundantly recompute the full batch."""
    pipe_size = mesh.shape[pipe_axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] % pipe_size:
            raise ValueError(
                "stacked stage dim %d not divisible by %s axis size %d"
                % (leaf.shape[0], pipe_axis, pipe_size))
    pspec = jax.tree_util.tree_map(lambda _: P(pipe_axis), stacked_params)
    xspec = P(batch_axis) if batch_axis else P()

    def fn(params, xs):
        def superstage(p, h):
            return lax.scan(lambda hh, pk: (stage_fn(pk, hh), None),
                            h, p)[0]
        return pipeline_apply(superstage, params, xs, pipe_axis,
                              n_microbatches)

    return shard_map(fn, mesh=mesh, in_specs=(pspec, xspec),
                     out_specs=xspec, check_vma=False)(stacked_params, x)

def pipeline_train_1f1b(stage_fn, first_fn, last_fn, params, x, y,
                        axis_name, n_microbatches):
    """Inside shard_map over ``axis_name``: one 1F1B training step.

    ``params = (p_first, p_blocks, p_last)``: ``p_blocks`` is THIS
    device's stacked block segment [k, ...] (run sequentially as a
    superstage); ``p_first`` / ``p_last`` are replicated but *computed*
    only on the boundary devices (``lax.cond`` keeps the untaken branch
    off the device's critical path).  ``first_fn(p, x_mb) -> h`` maps
    raw microbatch input (e.g. int tokens) to the inter-stage activation
    shape; ``stage_fn(p_block, h) -> h``; ``last_fn(p, h, y_mb) ->
    scalar mean-over-microbatch loss``.

    Schedule: fwd microbatch ``f = t - me`` and bwd microbatch
    ``j = t - 2(S-1) + me`` per tick — the last stage backpropagates a
    microbatch the same tick its forward finishes, cotangents hop one
    stage per tick, so device ``me`` holds at most ``2(S-1-me)``
    stashed inputs (O(S), vs O(M) for autodiff-through-GPipe).
    Returns ``(mean_loss, (g_first, g_blocks, g_last))``; boundary
    grads are psum'd (every device returns the true value)."""
    p_first, p_blocks, p_last = params
    s = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    m = n_microbatches
    if x.shape[0] % m:
        raise ValueError("batch %d %% n_microbatches %d != 0"
                         % (x.shape[0], m))
    mb = x.shape[0] // m
    xs = x.reshape((m, mb) + x.shape[1:])
    ys = y.reshape((m, mb) + y.shape[1:])
    fwd_pairs = [(i, i + 1) for i in range(s - 1)]
    bwd_pairs = [(i, i - 1) for i in range(1, s)]
    n_stash = 2 * (s - 1) + 1
    n_ticks = m + 2 * (s - 1)

    def seg_fwd(pf, pb, x_mb, h_recv):
        """This device's segment: embed on stage 0, then its blocks."""
        h0 = lax.cond(me == 0, lambda: first_fn(pf, x_mb),
                      lambda: h_recv)
        return lax.scan(lambda h, pk: (stage_fn(pk, h), None), h0, pb)[0]

    # probe the inter-stage activation shape without running the scan
    h_shape = jax.eval_shape(first_fn, p_first, xs[0])

    def tick(carry, t):
        stash, recv_fwd, recv_bwd, acc, loss_sum = carry
        gf, gb, gl = acc

        # ---- forward sub-tick: microbatch f = t - me ----
        f = t - me
        f_ok = (f >= 0) & (f < m)
        f_idx = jnp.clip(f, 0, m - 1)
        h_out = seg_fwd(p_first, p_blocks, xs[f_idx], recv_fwd)
        stash = stash.at[f_idx % n_stash].set(
            jnp.where(f_ok, recv_fwd, stash[f_idx % n_stash]))

        # ---- backward sub-tick: microbatch j = t - 2(S-1) + me ----
        j = t - 2 * (s - 1) + me
        j_ok = (j >= 0) & (j < m)
        j_idx = jnp.clip(j, 0, m - 1)
        h_in = stash[j_idx % n_stash]
        out_j, pull = jax.vjp(
            lambda pf, pb, hr: seg_fwd(pf, pb, xs[j_idx], hr),
            p_first, p_blocks, h_in)

        def last_cotangent():
            loss_j, lpull = jax.vjp(
                lambda pl, ho: last_fn(pl, ho, ys[j_idx]), p_last, out_j)
            dpl, g_out = lpull(jnp.float32(1.0 / m))
            return loss_j / m, dpl, g_out

        def mid_cotangent():
            zl = jax.tree_util.tree_map(jnp.zeros_like, p_last)
            return jnp.float32(0.0), zl, recv_bwd

        loss_j, dpl, g_out = lax.cond(me == s - 1, last_cotangent,
                                      mid_cotangent)
        dpf, dpb, dh = pull(g_out)

        ok = j_ok.astype(jnp.float32)
        gf = jax.tree_util.tree_map(lambda a, d: a + ok * d, gf, dpf)
        gb = jax.tree_util.tree_map(lambda a, d: a + ok * d, gb, dpb)
        gl = jax.tree_util.tree_map(lambda a, d: a + ok * d, gl, dpl)
        loss_sum = loss_sum + jnp.where(j_ok, loss_j, 0.0)

        recv_fwd = lax.ppermute(h_out, axis_name, fwd_pairs)
        recv_bwd = lax.ppermute(dh, axis_name, bwd_pairs)
        return (stash, recv_fwd, recv_bwd, (gf, gb, gl), loss_sum), None

    zeros_like = jax.tree_util.tree_map(jnp.zeros_like, (p_first, p_blocks,
                                                         p_last))
    stash0 = jnp.zeros((n_stash,) + h_shape.shape, h_shape.dtype)
    recv0 = jnp.zeros(h_shape.shape, h_shape.dtype)
    carry0 = (stash0, recv0, recv0, zeros_like, jnp.float32(0.0))
    (_, _, _, (gf, gb, gl), loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(n_ticks))

    # boundary grads / loss live on one device each — broadcast
    loss = lax.psum(jnp.where(me == s - 1, loss_sum, 0.0), axis_name)
    gf = jax.tree_util.tree_map(
        lambda g: lax.psum(jnp.where(me == 0, g, 0.0), axis_name), gf)
    gl = jax.tree_util.tree_map(
        lambda g: lax.psum(jnp.where(me == s - 1, g, 0.0), axis_name), gl)
    return loss, (gf, gb, gl)


def pipeline_train_1f1b_sharded(stage_fn, first_fn, last_fn, params, x, y,
                                mesh, pipe_axis="pipe", n_microbatches=4,
                                batch_axis=None, block_specs=None):
    """Global 1F1B entry: ``params = (p_first, p_blocks_stacked,
    p_last)`` with the block leaves stacked [n_blocks, ...] and sharded
    over ``pipe_axis`` (k = n_blocks / pipe_size consecutive blocks per
    device, like ``pipeline_apply_sharded``); first/last replicated.
    Returns ``(mean_loss, grads)`` in the params structure — block
    grads sharded over ``pipe_axis``, ready for the optimizer.

    ``batch_axis``: shard the batch dim over a data axis too; grads are
    pmean'd and the loss averaged across data slices.

    ``block_specs``: per-leaf PartitionSpecs for the block stack when a
    stage is ALSO tensor-parallel — e.g. ``{"w": P("pipe", None,
    "model"), "b": P("pipe")}`` column-shards each block's matrix over
    a ``model`` axis; ``stage_fn`` then uses the model axis's
    collectives (all_gather/psum) exactly as a Megatron layer would,
    and block grads come back in the same sharding."""
    p_first, p_blocks, p_last = params
    pipe_size = mesh.shape[pipe_axis]
    for leaf in jax.tree_util.tree_leaves(p_blocks):
        if leaf.shape[0] % pipe_size:
            raise ValueError(
                "stacked stage dim %d not divisible by %s axis size %d"
                % (leaf.shape[0], pipe_axis, pipe_size))
    if block_specs is not None:
        for spec in jax.tree_util.tree_leaves(
                block_specs, is_leaf=lambda s: isinstance(s, P)):
            if not spec or spec[0] != pipe_axis:
                # a spec that misses pipe on the stage dim would make
                # shard_map replicate the FULL block stack to every
                # device — each stage then runs the whole network:
                # silently wrong numbers, so fail loudly instead
                raise ValueError(
                    "block_specs leaf %s must shard its leading "
                    "(stage) dim over %r" % (spec, pipe_axis))
    bspec = (block_specs if block_specs is not None else
             jax.tree_util.tree_map(lambda _: P(pipe_axis), p_blocks))
    rspec_f = jax.tree_util.tree_map(lambda _: P(), p_first)
    rspec_l = jax.tree_util.tree_map(lambda _: P(), p_last)
    xspec = P(batch_axis) if batch_axis else P()

    def fn(pf, pb, pl, xx, yy):
        loss, (gf, gb, gl) = pipeline_train_1f1b(
            stage_fn, first_fn, last_fn, (pf, pb, pl), xx, yy,
            pipe_axis, n_microbatches)
        if batch_axis:
            loss = lax.pmean(loss, batch_axis)
            gf, gb, gl = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, batch_axis), (gf, gb, gl))
        return loss, (gf, gb, gl)

    return shard_map(
        fn, mesh=mesh,
        in_specs=(rspec_f, bspec, rspec_l, xspec, xspec),
        out_specs=(P(), (rspec_f, bspec, rspec_l)),
        check_vma=False)(p_first, p_blocks, p_last, x, y)


def pipeline_train_1f1b_interleaved(stage_fn, first_fn, last_fn, params,
                                    x, y, axis_name, n_microbatches,
                                    n_chunks):
    """Interleaved 1F1B (Megatron virtual stages, Narayanan et al.
    2021) inside shard_map: each device holds ``n_chunks`` block
    chunks spaced S apart (stage k = chunk*S + device), shrinking the
    pipeline bubble below plain 1F1B for the same microbatch count at
    the cost of ~v x ppermute traffic.  The schedule is NOT derived
    inline: ``interleave.build_schedule`` simulates and VERIFIES the
    tick-by-tick unit/recv-slot timing host-side and this function
    merely replays its [D, T] tables (``table[me, t]`` lookups), so a
    scheduling bug is a loud build-time exception.

    ``params = (p_first, p_blocks, p_last)`` with p_blocks the
    device's [v, k_per_chunk, ...] chunk stack; first_fn/last_fn are
    cond-gated onto virtual stage 0 / S*v-1 exactly as in
    ``pipeline_train_1f1b``.  Returns (mean_loss, grads)."""
    from veles_tpu.parallel.interleave import build_schedule

    p_first, p_blocks, p_last = params
    s = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    m, v = n_microbatches, n_chunks
    if x.shape[0] % m:
        raise ValueError("batch %d %% n_microbatches %d != 0"
                         % (x.shape[0], m))
    tab = build_schedule(s, v, m)
    T, ns = tab["n_ticks"], tab["n_stash"]
    pad = jnp.full((s, 1), -1, jnp.int32)
    fwd_c = jnp.asarray(tab["fwd_chunk"])
    fwd_m = jnp.asarray(tab["fwd_mb"])
    bwd_c = jnp.asarray(tab["bwd_chunk"])
    bwd_m = jnp.asarray(tab["bwd_mb"])
    # shifted so slot_x[me, t] = where the value received at the END of
    # tick t (consumable from t+1) lands; final-tick sends discard
    store_f = jnp.concatenate([jnp.asarray(tab["store_f"])[:, 1:], pad],
                              axis=1)
    store_b = jnp.concatenate([jnp.asarray(tab["store_b"])[:, 1:], pad],
                              axis=1)

    mb = x.shape[0] // m
    xs = x.reshape((m, mb) + x.shape[1:])
    ys = y.reshape((m, mb) + y.shape[1:])
    ring_f = [(i, (i + 1) % s) for i in range(s)]
    ring_b = [(i, (i - 1) % s) for i in range(s)]
    h_shape = jax.eval_shape(first_fn, p_first, xs[0])

    def seg_fwd(pf, pb, c, x_mb, h_in):
        h0 = lax.cond((me == 0) & (c == 0),
                      lambda: first_fn(pf, x_mb), lambda: h_in)
        chunk = jax.tree_util.tree_map(lambda a: a[c], pb)
        return lax.scan(lambda h, pk: (stage_fn(pk, h), None), h0,
                        chunk)[0]

    def tick(carry, t):
        stash, recv_f, recv_b, acc, loss_sum = carry
        gf, gb, gl = acc

        # ---- forward sub-tick: unit (fwd_c, fwd_m)[me, t] ----
        c_f = fwd_c[me, t]
        m_f = fwd_m[me, t]
        f_ok = m_f >= 0
        ci = jnp.clip(c_f, 0, v - 1)
        mi = jnp.clip(m_f, 0, m - 1)
        h_in = recv_f[ci]
        h_out = seg_fwd(p_first, p_blocks, ci, xs[mi], h_in)
        stash = stash.at[ci, mi % ns].set(
            jnp.where(f_ok, h_in, stash[ci, mi % ns]))

        # ---- backward sub-tick: unit (bwd_c, bwd_m)[me, t] ----
        c_b = bwd_c[me, t]
        m_b = bwd_m[me, t]
        b_ok = m_b >= 0
        cbi = jnp.clip(c_b, 0, v - 1)
        mbi = jnp.clip(m_b, 0, m - 1)
        h_in_b = stash[cbi, mbi % ns]
        out_b, pull = jax.vjp(
            lambda pf, pb, hr: seg_fwd(pf, pb, cbi, xs[mbi], hr),
            p_first, p_blocks, h_in_b)

        def last_cotangent():
            loss_j, lpull = jax.vjp(
                lambda pl, ho: last_fn(pl, ho, ys[mbi]), p_last, out_b)
            dpl, g_out = lpull(jnp.float32(1.0 / m))
            return loss_j / m, dpl, g_out

        def mid_cotangent():
            zl = jax.tree_util.tree_map(jnp.zeros_like, p_last)
            return jnp.float32(0.0), zl, recv_b[cbi]

        loss_j, dpl, g_out = lax.cond(
            (me == s - 1) & (cbi == v - 1), last_cotangent,
            mid_cotangent)
        dpf, dpb, dh = pull(g_out)
        ok = b_ok.astype(jnp.float32)
        gf = jax.tree_util.tree_map(lambda a, d: a + ok * d, gf, dpf)
        gb = jax.tree_util.tree_map(lambda a, d: a + ok * d, gb, dpb)
        gl = jax.tree_util.tree_map(lambda a, d: a + ok * d, gl, dpl)
        loss_sum = loss_sum + jnp.where(b_ok, loss_j, 0.0)

        # ---- ring hops + verified recv-slot stores ----
        got_f = lax.ppermute(h_out, axis_name, ring_f)
        got_b = lax.ppermute(dh, axis_name, ring_b)
        sf = store_f[me, t]
        sb = store_b[me, t]
        recv_f = recv_f.at[jnp.clip(sf, 0, v - 1)].set(
            jnp.where(sf >= 0, got_f,
                      recv_f[jnp.clip(sf, 0, v - 1)]))
        recv_b = recv_b.at[jnp.clip(sb, 0, v - 1)].set(
            jnp.where(sb >= 0, got_b,
                      recv_b[jnp.clip(sb, 0, v - 1)]))
        return (stash, recv_f, recv_b, (gf, gb, gl), loss_sum), None

    zeros = jax.tree_util.tree_map(jnp.zeros_like,
                                   (p_first, p_blocks, p_last))
    stash0 = jnp.zeros((v, ns) + h_shape.shape, h_shape.dtype)
    recv0 = jnp.zeros((v,) + h_shape.shape, h_shape.dtype)
    carry0 = (stash0, recv0, recv0, zeros, jnp.float32(0.0))
    (_, _, _, (gf, gb, gl), loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(T))
    loss = lax.psum(jnp.where(me == s - 1, loss_sum, 0.0), axis_name)
    gf = jax.tree_util.tree_map(
        lambda g: lax.psum(jnp.where(me == 0, g, 0.0), axis_name), gf)
    gl = jax.tree_util.tree_map(
        lambda g: lax.psum(jnp.where(me == s - 1, g, 0.0), axis_name),
        gl)
    return loss, (gf, gb, gl)


def pipeline_train_interleaved_sharded(stage_fn, first_fn, last_fn,
                                       params, x, y, mesh,
                                       pipe_axis="pipe",
                                       n_microbatches=4, n_chunks=2,
                                       batch_axis=None):
    """Global interleaved-1F1B entry: block leaves stacked
    [n_blocks, ...] with n_blocks = pipe * n_chunks * k; device d's
    chunk c holds blocks [(c*pipe + d) * k : ...] — the round-robin
    layout that puts stage k on device k %% pipe.  Returns
    (mean_loss, grads) with block grads sharded over ``pipe_axis``."""
    p_first, p_blocks, p_last = params
    pipe = mesh.shape[pipe_axis]
    for leaf in jax.tree_util.tree_leaves(p_blocks):
        if leaf.shape[0] % (pipe * n_chunks):
            raise ValueError(
                "stacked stage dim %d not divisible by pipe*chunks %d"
                % (leaf.shape[0], pipe * n_chunks))

    # reorder blocks so each device's shard is its [v, kpc] chunk
    # stack: global block (c*pipe + d)*kpc + j  ->  shard index
    # d*(v*kpc) + c*kpc + j
    def to_chunks(leaf):
        n = leaf.shape[0]
        kpc = n // (pipe * n_chunks)
        a = leaf.reshape((n_chunks, pipe, kpc) + leaf.shape[1:])
        a = jnp.moveaxis(a, 1, 0)       # [pipe, v, kpc, ...]
        return a.reshape((pipe * n_chunks * kpc,) + leaf.shape[1:])

    def from_chunks(leaf):
        n = leaf.shape[0]
        kpc = n // (pipe * n_chunks)
        a = leaf.reshape((pipe, n_chunks, kpc) + leaf.shape[1:])
        a = jnp.moveaxis(a, 0, 1)
        return a.reshape((n,) + leaf.shape[1:])

    pb_r = jax.tree_util.tree_map(to_chunks, p_blocks)
    bspec = jax.tree_util.tree_map(lambda _: P(pipe_axis), p_blocks)
    rspec_f = jax.tree_util.tree_map(lambda _: P(), p_first)
    rspec_l = jax.tree_util.tree_map(lambda _: P(), p_last)
    xspec = P(batch_axis) if batch_axis else P()

    def fn(pf, pb, pl, xx, yy):
        # local shard [v*kpc, ...] -> [v, kpc, ...]
        pb_local = jax.tree_util.tree_map(
            lambda a: a.reshape((n_chunks, a.shape[0] // n_chunks)
                                + a.shape[1:]), pb)
        loss, (gf, gb, gl) = pipeline_train_1f1b_interleaved(
            stage_fn, first_fn, last_fn, (pf, pb_local, pl), xx, yy,
            pipe_axis, n_microbatches, n_chunks)
        gb = jax.tree_util.tree_map(
            lambda a: a.reshape((a.shape[0] * a.shape[1],)
                                + a.shape[2:]), gb)
        if batch_axis:
            loss = lax.pmean(loss, batch_axis)
            gf, gb, gl = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, batch_axis), (gf, gb, gl))
        return loss, (gf, gb, gl)

    loss, (gf, gb, gl) = shard_map(
        fn, mesh=mesh,
        in_specs=(rspec_f, bspec, rspec_l, xspec, xspec),
        out_specs=(P(), (rspec_f, bspec, rspec_l)),
        check_vma=False)(p_first, pb_r, p_last, x, y)
    return loss, (gf, jax.tree_util.tree_map(from_chunks, gb), gl)
