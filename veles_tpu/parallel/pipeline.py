"""Pipeline parallelism: GPipe-style microbatch pipelining over a
``pipe`` mesh axis.

New capability beyond the reference (SURVEY.md §2.6: PP absent there).
Each device holds ONE stage's parameters; activations flow stage-to-stage
with ``lax.ppermute`` (one ICI neighbor hop per tick) while microbatches
stream through, so all stages compute concurrently after the fill phase —
the classic GPipe schedule with bubble fraction (S-1)/(M+S-1).

Constraint: every stage maps activations to the SAME shape (the
transformer-block regime pipelining is used for); embed/head layers live
outside the pipelined segment.  The whole schedule is a ``lax.scan``, so
it jits, differentiates (reverse-mode re-runs the scan), and composes
with the other mesh axes."""

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import PartitionSpec as P


def pipeline_apply(stage_fn, stage_params, x, axis_name, n_microbatches):
    """Inside shard_map over ``axis_name``: run the pipeline.

    stage_fn(stage_params, h) -> h (same shape); ``stage_params`` are THIS
    device's stage weights; ``x`` [B, ...] is the full batch (meaningful on
    stage 0, replicated elsewhere).  Returns [B, ...] outputs of the last
    stage, broadcast to every stage."""
    s = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError("batch %d %% n_microbatches %d != 0"
                         % (b, n_microbatches))
    mb = b // n_microbatches
    xs = x.reshape((n_microbatches, mb) + x.shape[1:])
    n_ticks = n_microbatches + s - 1
    fwd = [(i, i + 1) for i in range(s - 1)]   # no wraparound

    def tick(carry, t):
        outputs, recv = carry
        mb_idx = jnp.clip(t, 0, n_microbatches - 1)
        inp = jnp.where(me == 0, xs[mb_idx], recv)
        out = stage_fn(stage_params, inp)
        # the last stage finishes microbatch t-(s-1) at tick t
        out_idx = jnp.clip(t - (s - 1), 0, n_microbatches - 1)
        write = (me == s - 1) & (t >= s - 1)
        outputs = outputs.at[out_idx].set(
            jnp.where(write, out, outputs[out_idx]))
        recv = lax.ppermute(out, axis_name, fwd)
        return (outputs, recv), None

    outputs = jnp.zeros_like(xs)
    recv0 = jnp.zeros_like(xs[0])
    (outputs, _), _ = lax.scan(tick, (outputs, recv0),
                               jnp.arange(n_ticks))
    # broadcast the last stage's outputs to every device
    y = lax.psum(jnp.where(me == s - 1, outputs, 0.0), axis_name)
    return y.reshape(x.shape)


def pipeline_apply_sharded(stage_fn, stacked_params, x, mesh,
                           pipe_axis="pipe", n_microbatches=4,
                           batch_axis=None):
    """Global entry: ``stacked_params`` has a leading stage axis [S, ...]
    on every leaf, sharded over ``pipe_axis``.  With S == pipe size each
    device keeps one stage; with S == k * pipe size each device keeps k
    consecutive stages and runs them as one scanned "superstage" (fewer
    ICI hops, same math).  jit/grad-composable.

    ``batch_axis``: on a combined {data, pipe} mesh, shard x's batch dim
    over the data axis — each data slice streams ITS OWN microbatches
    through an independent pipeline (the ppermute hops stay within each
    data row of the mesh); without it x replicates and the data axis
    would redundantly recompute the full batch."""
    pipe_size = mesh.shape[pipe_axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] % pipe_size:
            raise ValueError(
                "stacked stage dim %d not divisible by %s axis size %d"
                % (leaf.shape[0], pipe_axis, pipe_size))
    pspec = jax.tree_util.tree_map(lambda _: P(pipe_axis), stacked_params)
    xspec = P(batch_axis) if batch_axis else P()

    def fn(params, xs):
        def superstage(p, h):
            return lax.scan(lambda hh, pk: (stage_fn(pk, hh), None),
                            h, p)[0]
        return pipeline_apply(superstage, params, xs, pipe_axis,
                              n_microbatches)

    return shard_map(fn, mesh=mesh, in_specs=(pspec, xspec),
                     out_specs=xspec, check_vma=False)(stacked_params, x)
