"""Interleaved-1F1B schedule tables (Megatron-LM's virtual-stage
schedule, Narayanan et al. 2021) for the lockstep-scan pipeline.

With ``v`` model chunks per device the pipeline has P = S*v virtual
stages; stage k lives on device k %% S as its chunk k // S.  Each
device's unit order is the standard interleaved sequence (S-microbatch
groups round-robining through its chunks), which shrinks the bubble
from (S-1)/(M+S-1) to (S-1)/(v*M+S-1)-ish at the cost of ~v x the
ppermute traffic.

Rather than trusting a closed-form tick alignment, ``build_schedule``
SIMULATES the execution under the lockstep constraints (one fwd and
one bwd sub-tick per device per tick; an activation/cotangent hop
arrives one tick after it is sent) and VERIFIES producer->consumer
timing unit by unit, returning dense [D, T] tables the SPMD scan
replays with ``table[me, t]`` lookups.  A schedule bug is therefore a
loud host-side exception at build time, never silent corruption on
the mesh."""

import numpy as np


def unit_order(s, v, m):
    """Megatron interleaved unit order (identical on every device):
    the i-th fwd (or bwd) unit's (chunk, microbatch).  Forward walks
    chunks 0..v-1 in S-microbatch groups, backward walks v-1..0."""
    fwd, bwd = [], []
    for i in range(m * v):
        group, r = divmod(i, s)
        cyc, chunk = divmod(group, v)
        fwd.append((chunk, cyc * s + r))
        bwd.append((v - 1 - chunk, cyc * s + r))
    return fwd, bwd


def warmup_units(d, s, v, m):
    """Fwd units device ``d`` runs before its first bwd (Megatron:
    rate-matches the last stage's first backward)."""
    return min((s - d - 1) * 2 + (v - 1) * s, m * v)


def build_schedule(s, v, m):
    """Simulate + verify; returns dict of [D, T] int32 tables:
    ``fwd_chunk/fwd_mb/bwd_chunk/bwd_mb`` (-1 = idle sub-tick) and the
    tick count T.  Raises if any unit's input would not have arrived
    exactly by its tick (the lockstep single-buffer contract)."""
    if m % s:
        raise ValueError("n_microbatches %d must be a multiple of the "
                         "pipe size %d for the interleaved schedule"
                         % (m, s))
    order_f, order_b = unit_order(s, v, m)
    warm = [warmup_units(d, s, v, m) for d in range(s)]
    # event-driven simulation: fwd_done[(k, mb)] / bwd_done -> tick
    fwd_done, bwd_done = {}, {}
    fi = [0] * s                    # next fwd unit index per device
    bi = [0] * s
    sched_f = []
    sched_b = []
    t = 0
    limit = 4 * (m * v + 2 * s * v) + 16
    while (any(i < m * v for i in fi) or any(i < m * v for i in bi)) \
            and t < limit:
        row_f = [(-1, -1)] * s
        row_b = [(-1, -1)] * s
        for d in range(s):
            # fwd sub-tick: run the next fwd unit if its input arrived
            if fi[d] < m * v:
                chunk, mb = order_f[fi[d]]
                k = chunk * s + d
                ready = (k == 0 or fwd_done.get((k - 1, mb), t) < t)
                # 1F1B pacing: past warmup, a fwd waits for its paired
                # bwd slot (one fwd per bwd) — run fwd only if we have
                # not outrun the backward stream by more than warmup
                paced = fi[d] < warm[d] + bi[d] + 1
                if ready and paced:
                    row_f[d] = (chunk, mb)
            if bi[d] < m * v:
                chunk, mb = order_b[bi[d]]
                k = chunk * s + d
                last = s * v - 1
                own_fwd = fwd_done.get((k, mb))
                if own_fwd is None and row_f[d] == (chunk, mb):
                    own_fwd = t      # fwd sub-tick precedes bwd in-tick
                if k == last:
                    ready = own_fwd is not None and own_fwd <= t
                else:
                    ready = (own_fwd is not None and own_fwd <= t
                             and bwd_done.get((k + 1, mb), t) < t)
                if ready:
                    row_b[d] = (chunk, mb)
        # commit the tick
        for d in range(s):
            if row_f[d][0] >= 0:
                chunk, mb = row_f[d]
                fwd_done[(chunk * s + d, mb)] = t
                fi[d] += 1
            if row_b[d][0] >= 0:
                chunk, mb = row_b[d]
                bwd_done[(chunk * s + d, mb)] = t
                bi[d] += 1
        sched_f.append(row_f)
        sched_b.append(row_b)
        t += 1
    if t >= limit:
        raise RuntimeError(
            "interleaved schedule did not converge (s=%d v=%d m=%d): "
            "fi=%s bi=%s" % (s, v, m, fi, bi))
    # verification: every non-first stage's fwd input was produced on
    # the PREVIOUS tick or earlier; every consumer's recv buffer holds
    # at most the latest hop (producer sent at exactly consumer_tick-1
    # OR the value sat in the buffer undisturbed — check no overwrite:
    # between production+1 and consumption, the producing device sent
    # no OTHER unit to the same consumer direction)
    def check_stream(done, direction):
        # recv buffers are PER CHUNK on the consumer: overwrite only
        # matters among hops landing in the same (dst, chunk) slot
        sends = {}        # (src, dst, dst_chunk) -> [(tick, unit)]
        for (k, mb), tick in done.items():
            if direction == "f" and k + 1 < s * v:
                kc = k + 1
                sends.setdefault((k % s, kc % s, kc // s), []).append(
                    (tick, (k, mb)))
            if direction == "b" and k > 0:
                kc = k - 1
                sends.setdefault((k % s, kc % s, kc // s), []).append(
                    (tick, (k, mb)))
        for slot, lst in sends.items():
            lst.sort()
            for (t1, u1), (t2, u2) in zip(lst, lst[1:]):
                # u1 is read at the START of its consumer's tick tc;
                # u2's store lands at the END of tick t2 — any t2 < tc
                # clobbers u1 before the read (t2 == tc stores after)
                k1, mb1 = u1
                kc = k1 + 1 if direction == "f" else k1 - 1
                tc = done.get((kc, mb1))
                if tc is not None and t2 < tc:
                    raise RuntimeError(
                        "recv slot overwrite on %s (%s): unit %s "
                        "(sent t=%d, consumed t=%d) clobbered by %s "
                        "(sent t=%d)" % (slot, direction,
                                         u1, t1, tc, u2, t2))
        # consumption causality
        for (k, mb), tick in done.items():
            kc = k + 1 if direction == "f" else k - 1
            if 0 <= kc < s * v:
                tc = done.get((kc, mb))
                if tc is not None and tc <= tick:
                    raise RuntimeError(
                        "causality violation (%s): stage %d mb %d at "
                        "t=%d but stage %d ran at t=%d"
                        % (direction, k, mb, tick, kc, tc))
    check_stream(fwd_done, "f")
    check_stream(bwd_done, "b")
    out = {}
    for name, sched, j in (("fwd_chunk", sched_f, 0),
                           ("fwd_mb", sched_f, 1),
                           ("bwd_chunk", sched_b, 0),
                           ("bwd_mb", sched_b, 1)):
        out[name] = np.asarray(
            [[row[d][j] for row in sched] for d in range(s)], np.int32)
    out["n_ticks"] = t

    # recv-store tables: which PER-CHUNK slot the hop arriving at tick
    # t (sent at t-1 on the ring) lands in, -1 = discard.  The fwd ring
    # is d -> d+1 with s-1 -> 0 wraparound; a sender's destination
    # stage k+1 always lives on (src+1) %% s, so the ring delivery and
    # the stage topology agree by construction.
    store_f = -np.ones((s, t), np.int32)
    store_b = -np.ones((s, t), np.int32)
    for tick in range(1, t):
        for dst in range(s):
            src = (dst - 1) % s
            c_s, _ = sched_f[tick - 1][src]
            if c_s >= 0 and c_s * s + src + 1 < s * v:
                store_f[dst, tick] = (c_s * s + src + 1) // s
            src = (dst + 1) % s
            c_s, _ = sched_b[tick - 1][src]
            if c_s >= 0 and c_s * s + src > 0:
                store_b[dst, tick] = (c_s * s + src - 1) // s
    out["store_f"] = store_f
    out["store_b"] = store_b

    # stash depth: max concurrently in-flight (fwd done, bwd pending)
    # microbatches over every (device, chunk)
    n_stash = 1
    for d in range(s):
        for c in range(v):
            k = c * s + d
            events = sorted(
                [(fwd_done[(k, mb)], 1) for mb in range(m)]
                + [(bwd_done[(k, mb)] + 0.5, -1) for mb in range(m)])
            live = peak = 0
            for _, delta in events:
                live += delta
                peak = max(peak, live)
            n_stash = max(n_stash, peak + 1)
    out["n_stash"] = n_stash
    return out


def bubble_fraction(s, v, m):
    """Measured bubble of the generated schedule: idle fwd+bwd
    sub-ticks over total sub-ticks."""
    tab = build_schedule(s, v, m)
    total = 2 * s * tab["n_ticks"]
    busy = int((tab["fwd_mb"] >= 0).sum() + (tab["bwd_mb"] >= 0).sum())
    return (total - busy) / total
