"""``shard_map`` compatibility shim.

jax moved ``shard_map`` from ``jax.experimental.shard_map`` to the
top-level namespace and renamed its replication-check kwarg
``check_rep`` → ``check_vma`` across the 0.4 → 0.6 line.  Every
shard_map in this repo imports from HERE so the per-version spelling
lives in exactly one place."""

import inspect

try:                                    # jax >= 0.6 top-level export
    from jax import shard_map as _shard_map
except ImportError:                     # 0.4.x: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

_CHECK_KW = ("check_vma" if "check_vma" in
             inspect.signature(_shard_map).parameters else "check_rep")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    if check_vma is not None:
        kw[_CHECK_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
