"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

New capability beyond the reference (SURVEY.md §5: long-context and
sequence parallelism are absent there — it predates them); mandated
first-class for the TPU build.  Two strategies over a named mesh axis
(``seq``), both SPMD via ``shard_map``:

- **Ring attention** (Liu et al.): every device holds a sequence shard of
  q, k, v.  The k/v shard rotates around the ring with ``lax.ppermute``
  (XLA lowers this to ICI neighbor exchange) while each device folds the
  visiting shard into its online-softmax accumulator
  (ops.attention.blockwise_attention carry) — full attention with O(T/n)
  activations per chip and communication overlapped with compute by XLA's
  latency-hiding scheduler.  Exact, not approximate: the online-softmax
  merge is associative.

- **Ulysses** (all-to-all): resharding [B, H, T/n, D] → [B, H/n, T, D]
  with ``lax.all_to_all``, full attention on the head shard, then the
  inverse all-to-all.  Cheaper collectives for moderate T when
  n_heads % n_devices == 0.

Both take already-sharded per-device arrays inside ``shard_map``; the
``*_sharded`` wrappers build the shard_map over a Mesh for callers holding
global arrays."""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from veles_tpu.parallel.smap import shard_map

from veles_tpu.ops import attention as att


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   block_k=512):
    """Inside shard_map: q, k, v are the local [B, H, T/n, D] shards,
    sequence-sharded over ``axis_name``.  Returns the local output shard.
    """
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    t_local = q.shape[-2]
    q_offset = me * t_local
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, state):
        acc, m, l, kk, vv = state
        # after i rotations we hold the shard originally on device me - i
        src = (me - i) % n

        def fold(carry):
            return att.blockwise_attention(
                q, kk, vv, causal=causal, scale=scale, block_k=block_k,
                q_offset=q_offset, k_offset=src * t_local,
                carry=carry, return_carry=True)

        if causal:
            # a visiting shard entirely in the future (src > me) is fully
            # masked — skip its einsums, pass the carry through (saves
            # ~half the attention FLOPs per step on average)
            acc, m, l = lax.cond(src > me, lambda c: c, fold, (acc, m, l))
        else:
            acc, m, l = fold((acc, m, l))
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return acc, m, l, kk, vv

    b, h, _, d = q.shape
    acc = jnp.zeros((b, h, t_local, d), jnp.float32)
    m = jnp.full((b, h, t_local), att.NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t_local), jnp.float32)
    acc, m, l, _, _ = lax.fori_loop(
        0, n, step, (acc, m, l, k, v), unroll=True)
    return att.finalize_attention((acc, m, l)).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None):
    """Inside shard_map: all-to-all seq-sharded → head-sharded, full
    attention, inverse.  Requires n_heads % axis_size == 0."""
    n = lax.psum(1, axis_name)
    if q.shape[1] % n:
        raise ValueError("ulysses needs n_heads (%d) %% axis size (%d) == 0"
                         % (q.shape[1], n))
    def a2a_fwd(x):   # [B, H, T/n, D] -> [B, H/n, T, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)
    def a2a_bwd(x):   # [B, H/n, T, D] -> [B, H, T/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)
    o = att.blockwise_attention(a2a_fwd(q), a2a_fwd(k), a2a_fwd(v),
                                causal=causal, scale=scale)
    return a2a_bwd(o)


def _sharded(fn, mesh, seq_axis, **kw):
    spec = P(None, None, seq_axis, None)
    wrapped = functools.partial(fn, axis_name=seq_axis, **kw)
    return shard_map(wrapped, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)


def ring_attention_sharded(q, k, v, mesh, seq_axis="seq", causal=False,
                           scale=None, block_k=512):
    """Global [B, H, T, D] arrays; shard over ``seq_axis`` and run the
    ring.  jit-compatible (shard_map composes with jit/grad)."""
    return _sharded(ring_attention, mesh, seq_axis, causal=causal,
                    scale=scale, block_k=block_k)(q, k, v)


def ulysses_attention_sharded(q, k, v, mesh, seq_axis="seq", causal=False,
                              scale=None):
    return _sharded(ulysses_attention, mesh, seq_axis, causal=causal,
                    scale=scale)(q, k, v)
