"""Audio file loaders (ref veles/loader/libsndfile*.py — libsndfile decode
of wav/flac/ogg + windowing into fixed-size frames).

Decode prefers the ``soundfile`` package (libsndfile bindings) when
importable and falls back to the stdlib ``wave`` module for PCM WAV —
so the loader always works in this image.  Samples are normalized to
float32 in [-1, 1], mixed down to mono, and windowed into
``frame_size``-sample frames with ``frame_stride`` hop."""

import os
import wave

import numpy as np

from veles_tpu.loader.base import TEST, TRAIN, VALID
from veles_tpu.loader.fullbatch import FullBatchLoader

CLASS_KEYS = {"test": TEST, "validation": VALID, "train": TRAIN}


def read_audio(path):
    """→ (float32 mono samples in [-1, 1], sample_rate)."""
    try:
        import soundfile
        data, rate = soundfile.read(path, dtype="float32")
        if data.ndim > 1:
            data = data.mean(axis=1)
        return np.asarray(data, np.float32), rate
    except ImportError:
        pass
    with wave.open(path, "rb") as w:
        rate = w.getframerate()
        n = w.getnframes()
        width = w.getsampwidth()
        channels = w.getnchannels()
        raw = w.readframes(n)
    if width == 2:
        data = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 1:
        data = (np.frombuffer(raw, "u1").astype(np.float32) - 128.0) / 128.0
    elif width == 4:
        data = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError("unsupported sample width %d in %s" % (width, path))
    if channels > 1:
        data = data.reshape(-1, channels).mean(axis=1)
    return data, rate


def window(samples, frame_size, frame_stride=None):
    """Slice a 1-D signal into [n_frames, frame_size] windows."""
    stride = frame_stride or frame_size
    n = (len(samples) - frame_size) // stride + 1
    if n <= 0:
        return np.zeros((0, frame_size), np.float32)
    idx = np.arange(n)[:, None] * stride + np.arange(frame_size)[None, :]
    return samples[idx]


class AudioLoader(FullBatchLoader):
    """:param files: {class_name: [paths or (path, label) tuples]};
    every file is decoded, windowed, and stacked into the full batch.
    Labels default to the file's position in its list."""

    MAPPING = "audio"

    def __init__(self, workflow, files=None, frame_size=1024,
                 frame_stride=None, **kwargs):
        super(AudioLoader, self).__init__(workflow, **kwargs)
        self.files = files or {}
        self.frame_size = frame_size
        self.frame_stride = frame_stride
        self.sample_rates = {}

    def load_data(self):
        datas = [[], [], []]
        labels = [[], [], []]
        for key, entries in self.files.items():
            cls = CLASS_KEYS[key]
            for i, entry in enumerate(entries):
                path, label = (entry if isinstance(entry, tuple)
                               else (entry, i))
                samples, rate = read_audio(path)
                self.sample_rates[os.path.basename(path)] = rate
                frames = window(samples, self.frame_size, self.frame_stride)
                datas[cls].append(frames)
                labels[cls].extend([label] * len(frames))
        lengths = [sum(len(f) for f in datas[c]) for c in range(3)]
        if sum(lengths) == 0:
            raise ValueError("AudioLoader: no frames decoded")
        self.original_data = np.concatenate(
            [f for c in range(3) for f in datas[c]])
        self.original_labels = np.asarray(
            sum((labels[c] for c in range(3)), []), np.int32)
        self.class_lengths = lengths
