"""Format loaders (ref: veles/loader/loader_hdf5.py:48-151, pickles.py:55,
saver.py:69,182).

* HDF5Loader — datasets from .h5 files (one file per class, keys
  ``data``/``labels`` like the reference's test fixtures test.h5/train.h5)
* PickleLoader — (data, labels) tuples or dicts from .pkl files
* MinibatchesSaver / MinibatchesLoader — record a served minibatch stream
  to a compressed file and replay it later without the original pipeline
  (ref saver.py MinibatchesSaver 'compressed minibatch stream')."""

import gzip
import os
import pickle

import numpy as np

from veles_tpu.loader.base import TEST, TRAIN, VALID, Loader
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.units import Unit


class HDF5Loader(FullBatchLoader):
    """:param files: {class_name: path} with class_name in
    test/validation/train; each file holds ``data`` and optional
    ``labels`` datasets."""

    MAPPING = "hdf5"
    CLASS_KEYS = {"test": TEST, "validation": VALID, "train": TRAIN}

    def __init__(self, workflow, files=None, **kwargs):
        super(HDF5Loader, self).__init__(workflow, **kwargs)
        self.files = files or {}

    def load_data(self):
        import h5py
        datas = [None, None, None]
        labels = [None, None, None]
        lengths = [0, 0, 0]
        for key, path in self.files.items():
            cls = self.CLASS_KEYS[key]
            with h5py.File(path, "r") as f:
                datas[cls] = np.asarray(f["data"], np.float32)
                if "labels" in f:
                    labels[cls] = np.asarray(f["labels"], np.int32)
                lengths[cls] = len(datas[cls])
        self.original_data, self.original_labels = _assemble(datas, labels)
        self.class_lengths = lengths


def _assemble(datas, labels):
    """Concatenate per-class data; labels stay aligned with data — classes
    without a label file get zero labels so indices never misalign."""
    present = [(d, labels[i]) for i, d in enumerate(datas) if d is not None]
    if not present:
        raise ValueError("no dataset files given")
    data = np.concatenate([d for d, _ in present])
    if any(l is not None for _, l in present):
        label_parts = [l if l is not None else np.zeros(len(d), np.int32)
                       for d, l in present]
        return data, np.concatenate(label_parts)
    return data, None


class PickleLoader(FullBatchLoader):
    """Pickled dataset files: each unpickles to (data, labels) or
    {"data": ..., "labels": ...} (ref loader/pickles.py)."""

    MAPPING = "pickles"

    def __init__(self, workflow, files=None, **kwargs):
        super(PickleLoader, self).__init__(workflow, **kwargs)
        self.files = files or {}

    def load_data(self):
        datas = [None, None, None]
        labels = [None, None, None]
        lengths = [0, 0, 0]
        for key, path in self.files.items():
            cls = HDF5Loader.CLASS_KEYS[key]
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                obj = pickle.load(f)
            if isinstance(obj, dict):
                d, l = obj["data"], obj.get("labels")
            else:
                d, l = obj[0], (obj[1] if len(obj) > 1 else None)
            datas[cls] = np.asarray(d, np.float32)
            if l is not None:
                labels[cls] = np.asarray(l, np.int32)
            lengths[cls] = len(datas[cls])
        self.original_data, self.original_labels = _assemble(datas, labels)
        self.class_lengths = lengths


class MinibatchesSaver(Unit):
    """Records the loader's served minibatch stream (indices resolved to
    actual data) into a gzip pickle stream (ref saver.py:69)."""

    def __init__(self, workflow, path="minibatches.sav.gz", **kwargs):
        super(MinibatchesSaver, self).__init__(workflow, **kwargs)
        self.path = path
        self.demand("loader")
        self._file = None

    def initialize(self, **kwargs):
        os.makedirs(os.path.dirname(os.path.abspath(self.path)) or ".",
                    exist_ok=True)
        self._file = gzip.open(self.path, "wb")
        # one device→host transfer at initialize, not per minibatch
        self._host_data = np.asarray(self.loader.data)
        self._host_labels = (np.asarray(self.loader.labels)
                             if self.loader.labels is not None else None)
        header = {
            "minibatch_size": self.loader.minibatch_size,
            "class_lengths": list(self.loader.class_lengths),
            "sample_shape": tuple(self._host_data.shape[1:]),
        }
        pickle.dump(header, self._file)

    def run(self):
        loader = self.loader
        safe = np.maximum(loader.minibatch_indices, 0)
        record = {
            "cls": loader.minibatch_class,
            "data": self._host_data[safe],
            "labels": (self._host_labels[safe]
                       if self._host_labels is not None else None),
            "valid": loader.minibatch_valid.copy(),
        }
        pickle.dump(record, self._file)

    def stop(self):
        if self._file is not None:
            self._file.close()
            self._file = None


def read_minibatches(path):
    """Replay a MinibatchesSaver stream: yields (header, records)
    (ref MinibatchesLoader, saver.py:182)."""
    with gzip.open(path, "rb") as f:
        header = pickle.load(f)
        records = []
        while True:
            try:
                records.append(pickle.load(f))
            except EOFError:
                break
    return header, records


class MinibatchesLoader(Loader):
    """Serves a recorded minibatch stream in order — the dataset pipeline
    is replaced by the replay file (ref MinibatchesLoader)."""

    MAPPING = "minibatches"
    carries_data = True

    def __init__(self, workflow, path=None, **kwargs):
        super(MinibatchesLoader, self).__init__(workflow, **kwargs)
        self.path = path
        self.records = []
        self.position = 0

    def load_data(self):
        header, self.records = read_minibatches(self.path)
        self.minibatch_size = header["minibatch_size"]
        self.class_lengths = list(header["class_lengths"])
        self.sample_shape = header["sample_shape"]
        if not self.records:
            raise ValueError("empty minibatch stream %s" % self.path)

    def run(self):
        if bool(self.epoch_ended):
            self.epoch_ended <<= False
        if bool(self.last_minibatch):
            self.last_minibatch <<= False
        if bool(self.class_ended):
            self.class_ended <<= False
        rec = self.records[self.position]
        self.minibatch_class = rec["cls"]
        self.minibatch_data = rec["data"]
        self.minibatch_labels = rec["labels"]
        self.minibatch_valid = rec["valid"]
        self.minibatch_indices = None   # replay carries data directly
        self.position += 1
        if self.position >= len(self.records) or \
                self.records[self.position]["cls"] != rec["cls"]:
            self.class_ended <<= True
        if self.position >= len(self.records):
            self.last_minibatch <<= True
            self.epoch_ended <<= True
            self.epoch_number += 1
            self.position = 0
