"""FullBatchLoader — whole dataset resident in HBM
(ref: veles/loader/fullbatch.py:79; gather kernel ocl/fullbatch_loader.cl).

The reference gathered minibatches on-device with a custom kernel
(`fill_minibatch_data_labels`); here the gather is a ``jnp.take`` *inside*
the jitted train step, so it fuses with the first layer and the dataset
never leaves HBM.  Subclasses (or callers) provide numpy arrays; this class
places them on device once at initialize."""

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu.loader.base import TEST, TRAIN, VALID, Loader


class FullBatchLoader(Loader):
    MAPPING = "full_batch"

    def __init__(self, workflow, **kwargs):
        super(FullBatchLoader, self).__init__(workflow, **kwargs)
        #: numpy source arrays, set by subclass load_data() or kwargs
        self.original_data = kwargs.get("data")
        self.original_labels = kwargs.get("labels")
        self.original_targets = kwargs.get("targets")  # for MSE workflows
        self._kw_class_lengths = kwargs.get("class_lengths")
        #: device-resident dataset (jax arrays)
        self.data = None
        self.labels = None
        self.targets = None
        self.on_device = kwargs.get("on_device", True)

    def load_data(self):
        if self.original_data is None:
            raise ValueError("FullBatchLoader needs data= or a subclass "
                             "overriding load_data()")
        n = len(self.original_data)
        if self._kw_class_lengths is not None:
            self.class_lengths = list(self._kw_class_lengths)
        elif self.class_lengths == [0, 0, 0]:
            # default split: 10% validation, rest train (ref loaders often
            # get explicit validation_ratio; keep a sane default)
            n_valid = max(1, n // 10)
            self.class_lengths = [0, n_valid, n - n_valid]
        if sum(self.class_lengths) != n:
            raise ValueError("class_lengths %s != %d samples"
                             % (self.class_lengths, n))

    def create_minibatch_data(self):
        """One host→device transfer for the whole dataset (ref fullbatch
        on-device residency, fullbatch.py:164-242)."""
        if not self.on_device:
            self.data = np.asarray(self.original_data)
            self.labels = (None if self.original_labels is None
                           else np.asarray(self.original_labels))
            self.targets = (None if self.original_targets is None
                            else np.asarray(self.original_targets))
            return
        self.data = jnp.asarray(self.original_data)
        if self.original_labels is not None:
            self.labels = jnp.asarray(np.asarray(self.original_labels)
                                      .astype(np.int32))
        if self.original_targets is not None:
            self.targets = jnp.asarray(self.original_targets)

    @staticmethod
    def gather(dataset, indices):
        """Minibatch gather, used inside jitted steps: pad indices (-1)
        clamp to row 0 — their loss contribution is masked out by
        ``minibatch_valid``.  (TPU equivalent of
        ocl/fullbatch_loader.cl:1-50.)"""
        safe = jnp.maximum(indices, 0)
        return jnp.take(dataset, safe, axis=0)
