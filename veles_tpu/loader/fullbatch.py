"""FullBatchLoader — whole dataset resident in HBM
(ref: veles/loader/fullbatch.py:79; gather kernel ocl/fullbatch_loader.cl).

The reference gathered minibatches on-device with a custom kernel
(`fill_minibatch_data_labels`); here the gather is a ``jnp.take`` *inside*
the jitted train step, so it fuses with the first layer and the dataset
never leaves HBM.  Subclasses (or callers) provide numpy arrays; this class
places them on device once at initialize."""

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu.loader.base import TEST, TRAIN, VALID, Loader


class FullBatchLoader(Loader):
    MAPPING = "full_batch"

    def __init__(self, workflow, **kwargs):
        super(FullBatchLoader, self).__init__(workflow, **kwargs)
        #: numpy source arrays, set by subclass load_data() or kwargs
        self.original_data = kwargs.get("data")
        self.original_labels = kwargs.get("labels")
        self.original_targets = kwargs.get("targets")  # for MSE workflows
        self._kw_class_lengths = kwargs.get("class_lengths")
        #: device-resident dataset (jax arrays)
        self.data = None
        self.labels = None
        self.targets = None
        #: True → place in HBM, falling back to "host" on OOM (ref OOM
        #: fallback, veles/loader/fullbatch.py:164-242).
        #: False → keep numpy arrays, still an *index* loader (units
        #: gather from host memory — Kohonen/RBM style).
        #: "host" → become a data-carrying loader serving gathered numpy
        #: minibatches which the trainer streams to the device per step.
        #: "defer" → keep numpy; a mesh trainer will row-shard the arrays
        #: itself, so a single-device replica must never be created.
        self.on_device = kwargs.get("on_device", True)
        if self.on_device not in (True, False, "host", "defer"):
            raise ValueError("on_device must be True/False/'host'/'defer'")
        self.sample_shape = None         # set in host mode
        #: normalizer applied to the whole dataset before placement —
        #: fitted on the TRAIN span only (ref normalizer integration in
        #: Loader base, veles/loader/base.py:344-349); name or instance
        norm = kwargs.get("normalization", "none")
        if isinstance(norm, str):
            from veles_tpu.loader.normalization import make_normalizer
            norm = make_normalizer(norm)
        self.normalizer = norm

    def load_data(self):
        if self.original_data is None:
            raise ValueError("FullBatchLoader needs data= or a subclass "
                             "overriding load_data()")
        n = len(self.original_data)
        if self._kw_class_lengths is not None:
            self.class_lengths = list(self._kw_class_lengths)
        elif self.class_lengths == [0, 0, 0]:
            # default split: 10% validation, rest train (ref loaders often
            # get explicit validation_ratio; keep a sane default)
            n_valid = max(1, n // 10)
            self.class_lengths = [0, n_valid, n - n_valid]
        if sum(self.class_lengths) != n:
            raise ValueError("class_lengths %s != %d samples"
                             % (self.class_lengths, n))

    # -- base label analysis hooks (veles/loader/base.py:755-819) ---------
    def get_raw_labels(self):
        return self.original_labels

    def set_mapped_labels(self, mapped):
        self.original_labels = mapped

    def _normalize_dataset(self):
        from veles_tpu.loader.normalization import NoneNormalizer
        if isinstance(self.normalizer, NoneNormalizer) or \
                getattr(self, "_dataset_prenormalized", False):
            return   # subclass (image loader) normalized during decode
        data = np.asarray(self.original_data)
        train_start = self.class_offsets[1]   # after test+valid spans
        self.normalizer.analyze(data[train_start:] if
                                train_start < len(data) else data)
        self.original_data = self.normalizer.normalize(
            data).reshape(data.shape)

    def create_minibatch_data(self):
        """One host→device transfer for the whole dataset (ref fullbatch
        on-device residency, fullbatch.py:164-242).  On device OOM the
        loader degrades to host-streaming mode instead of dying."""
        self._normalize_dataset()
        if self.on_device is True:
            try:
                self.data = jnp.asarray(self.original_data)
                if self.original_labels is not None:
                    self.labels = jnp.asarray(
                        np.asarray(self.original_labels).astype(np.int32))
                if self.original_targets is not None:
                    self.targets = jnp.asarray(self.original_targets)
                return
            except Exception as e:
                msg = str(e)
                if not ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                        or "out of memory" in msg):
                    raise
                self.warning("dataset does not fit in HBM (%s) — falling "
                             "back to host-streaming mode", msg[:120])
                self.data = self.labels = self.targets = None
                self._enable_host_mode()
                return
        if self.on_device == "host":
            self._enable_host_mode()
            return
        # False / "defer": numpy arrays, still an index loader
        self.data = np.asarray(self.original_data)
        if self.original_labels is not None:
            self.labels = np.asarray(self.original_labels).astype(np.int32)
        if self.original_targets is not None:
            self.targets = np.asarray(self.original_targets)

    def _enable_host_mode(self):
        self.carries_data = True     # instance attr shadows the class attr
        self._host_data = np.ascontiguousarray(self.original_data)
        self._host_labels = (None if self.original_labels is None else
                             np.asarray(self.original_labels)
                             .astype(np.int32))
        self._host_targets = (None if self.original_targets is None
                              else np.asarray(self.original_targets))
        self.sample_shape = tuple(self._host_data.shape[1:])
        self.minibatch_data = None
        self.minibatch_labels = None
        self.minibatch_targets = None

    def run(self):
        super(FullBatchLoader, self).run()
        if not self.carries_data:
            return
        # host mode: gather the minibatch rows on the host (pad rows → 0;
        # their loss contribution is masked by minibatch_valid)
        idx = np.maximum(self.minibatch_indices, 0)
        self.minibatch_data = self._host_data[idx]
        if self._host_labels is not None:
            self.minibatch_labels = self._host_labels[idx]
        if self._host_targets is not None:
            self.minibatch_targets = self._host_targets[idx]

    @staticmethod
    def gather(dataset, indices):
        """Minibatch gather, used inside jitted steps: pad indices (-1)
        clamp to row 0 — their loss contribution is masked out by
        ``minibatch_valid``.  (TPU equivalent of
        ocl/fullbatch_loader.cl:1-50.)"""
        safe = jnp.maximum(indices, 0)
        return jnp.take(dataset, safe, axis=0)
