"""Loader — the minibatch-serving state machine (ref: veles/loader/base.py).

Keeps the reference's semantics: three sample classes TEST/VALID/TRAIN
(ref base.py:72-80), a global offset that walks test → valid → train every
epoch, per-epoch reshuffle of the train span (ref :711), and the
``last_minibatch`` / ``epoch_ended`` flags that drive Decision gates
(ref :862-879).

TPU-first differences: minibatches are *fixed shape* — the trailing partial
minibatch is padded and a validity mask is emitted instead of shrinking the
batch (XLA wants static shapes); the loader only produces **indices** (the
gather happens inside the jitted step against the HBM-resident dataset,
exactly how the reference's master serves indices to slaves,
ref base.py:631)."""

import numpy as np

from veles_tpu import prng
from veles_tpu.mutable import Bool
from veles_tpu.registry import MappedRegistry
from veles_tpu.units import Unit, UnitRegistry

TEST, VALID, TRAIN = 0, 1, 2
CLASS_NAMES = ("test", "validation", "train")


class LoaderRegistry(UnitRegistry, MappedRegistry):
    """Name → loader class (ref UserLoaderRegistry, loader/base.py:83)."""


class Loader(Unit, metaclass=LoaderRegistry):
    mapping = {}

    #: index loaders (False) serve minibatch_indices into an HBM-resident
    #: dataset; data-carrying loaders (True: streaming/replay) serve
    #: minibatch_data (+ minibatch_labels) directly and set sample_shape.
    carries_data = False

    def __init__(self, workflow, **kwargs):
        super(Loader, self).__init__(workflow, **kwargs)
        self.minibatch_size = kwargs.get("minibatch_size", 100)
        #: [n_test, n_valid, n_train]
        self.class_lengths = [0, 0, 0]
        self.epoch_number = 0
        self.epoch_ended = Bool(False)
        self.last_minibatch = Bool(False)
        #: True when the served minibatch was the final one of its class —
        #: Decision reads that class's accumulated stats on this signal
        self.class_ended = Bool(False)
        self.minibatch_class = TRAIN
        self.minibatch_indices = None    # int32 [minibatch_size], -1 = pad
        self.minibatch_valid = None      # float32 [minibatch_size] mask
        self.minibatch_offset = 0
        self.shuffle_enabled = kwargs.get("shuffle", True)
        self.prng = prng.get(kwargs.get("prng_name", "loader"))
        self._order = None
        #: optional (instance, train_ratio) — train on a per-instance
        #: random subset.  The CLI channel is root.common.ensemble
        #: (--ensemble-train children); this kwarg is the programmatic one.
        self.train_subset = kwargs.get("train_subset")

    # -- to be provided by subclasses ---------------------------------------
    def load_data(self):
        """Populate class_lengths (+ dataset payload).  Ref ILoader."""
        raise NotImplementedError

    def create_minibatch_data(self):
        """Optional post-load hook (device placement etc.)."""

    def get_raw_labels(self):
        """Per-sample label values in dataset order (any hashable type),
        or None.  Drives the base label analysis; FullBatchLoader returns
        its original_labels."""
        return None

    def set_mapped_labels(self, mapped):
        """Receive int32 class indices after label mapping.  Override in
        loaders that store labels."""

    # -- derived sizes -------------------------------------------------------
    @property
    def total_samples(self):
        return int(sum(self.class_lengths))

    @property
    def class_offsets(self):
        ofs, out = 0, []
        for ln in self.class_lengths:
            ofs += int(ln)
            out.append(ofs)
        return out  # cumulative end offsets per class

    def class_of_offset(self, offset):
        for cls, end in enumerate(self.class_offsets):
            if offset < end:
                return cls
        raise ValueError("offset %d beyond dataset" % offset)

    # -- lifecycle -----------------------------------------------------------
    def initialize(self, **kwargs):
        self.load_data()
        if self.total_samples == 0:
            raise ValueError("%s loaded an empty dataset" % self)
        self._analyze_labels()
        self._apply_ensemble_subset()
        if self.minibatch_size > max(self.class_lengths):
            self.minibatch_size = int(max(self.class_lengths))
        self.create_minibatch_data()
        self._reset_order()
        self.minibatch_offset = 0
        self.epoch_number = 0
        self.debug("dataset: %s samples %s",
                   self.total_samples,
                   dict(zip(CLASS_NAMES, self.class_lengths)))

    def _analyze_labels(self):
        """Dataset label analysis (ref veles/loader/base.py:755-819):
        arbitrary label values (strings, sparse ints, ...) map to dense
        class indices; per-class counts per split are recorded and
        validated — labels appearing in eval splits but never trained on
        are warned about, as is heavy class skew."""
        raw = self.get_raw_labels()
        self.labels_mapping = None
        self.label_distribution = None
        if raw is None:
            return
        raw = np.asarray(raw)
        if raw.ndim != 1:
            return   # sequence/dense label tensors (LM targets, frame
                     # labels) are not per-sample class ids
        if len(raw) != self.total_samples:
            raise ValueError("%d labels for %d samples"
                             % (len(raw), self.total_samples))
        unique_arr, inverse = np.unique(raw, return_inverse=True)
        uniques = unique_arr.tolist()
        self.labels_mapping = {u: i for i, u in enumerate(uniques)}
        if (np.issubdtype(unique_arr.dtype, np.integer)
                and uniques == list(range(len(uniques)))):
            mapped = raw.astype(np.int32)    # already dense class ids
        else:
            mapped = inverse.astype(np.int32)
            self.info("mapped %d distinct label values to class indices "
                      "0..%d", len(uniques), len(uniques) - 1)
        self.set_mapped_labels(mapped)
        # per-split distribution + validation
        dist = {}
        spans = [(0, self.class_offsets[TEST]),
                 (self.class_offsets[TEST], self.class_offsets[VALID]),
                 (self.class_offsets[VALID], self.total_samples)]
        for cls, (lo, hi) in zip(CLASS_NAMES, spans):
            if hi > lo:
                counts = np.bincount(mapped[lo:hi], minlength=len(uniques))
                dist[cls] = {str(u): int(c)
                             for u, c in zip(uniques, counts)}
        self.label_distribution = dist
        train = dist.get("train")
        if train:
            untrained = [u for u, c in train.items() if c == 0]
            for other in ("test", "validation"):
                leaked = [u for u in untrained
                          if dist.get(other, {}).get(u, 0) > 0]
                if leaked:
                    self.warning("%s split contains classes never seen in "
                                 "training: %s", other, leaked[:10])
            counts = [c for c in train.values() if c]
            if counts and max(counts) > 10 * min(counts):
                self.warning("skewed class distribution in train: "
                             "min %d vs max %d samples",
                             min(counts), max(counts))

    def _apply_ensemble_subset(self):
        """Restrict the train span to a per-instance random subset (ref
        ensemble members training on random train subsets,
        veles/ensemble/model_workflow.py:137).  Source: the
        ``train_subset=(instance, ratio)`` kwarg, else the CLI channel
        root.common.ensemble set by --ensemble-train children.  Global
        sample indices stay valid — only the served order shrinks."""
        if self.train_subset is not None:
            instance, ratio = self.train_subset
        else:
            from veles_tpu.config import root
            ens = root.common.get("ensemble")
            if ens is None:
                return
            cfg = ens.as_dict() if hasattr(ens, "as_dict") else dict(ens)
            ratio = cfg.get("train_ratio", 1.0)
            instance = cfg.get("instance", 0)
        ratio = float(ratio)
        instance = int(instance)
        n_train = int(self.class_lengths[TRAIN])
        if ratio >= 1.0 or n_train <= 1:
            return
        n_sub = max(1, int(n_train * ratio))
        rs = np.random.RandomState(0xE75 + instance)
        start = self.class_offsets[VALID]   # train span starts here
        self._train_pool = np.sort(start + rs.choice(
            n_train, n_sub, replace=False).astype(np.int32))
        self.class_lengths[TRAIN] = n_sub
        self.info("ensemble instance %d: training on %d/%d samples",
                  instance, n_sub, n_train)

    def _reset_order(self):
        """Identity order for test/valid; reshuffled train span
        (ref base.py:711 shuffle per epoch).  With an ensemble subset the
        train span draws from the instance's pool of global indices."""
        order = np.arange(self.total_samples, dtype=np.int32)
        n_train = self.class_lengths[TRAIN]
        pool = getattr(self, "_train_pool", None)
        start = self.class_offsets[VALID]
        if pool is not None:
            order[start:] = pool
        if self.shuffle_enabled and n_train:
            perm = self.prng.permutation(n_train).astype(np.int32)
            order[start:] = order[start:][perm]
        self._order = order

    # -- the hot-loop step ---------------------------------------------------
    def run(self):
        """Serve the next minibatch's indices (ref serve_next_minibatch,
        base.py:726)."""
        if bool(self.epoch_ended):
            self.epoch_ended <<= False
        if bool(self.last_minibatch):
            self.last_minibatch <<= False
        if bool(self.class_ended):
            self.class_ended <<= False
        offset = self.minibatch_offset
        cls = self.class_of_offset(offset)
        end_of_class = self.class_offsets[cls]
        count = min(self.minibatch_size, end_of_class - offset)

        idx = np.full((self.minibatch_size,), -1, np.int32)
        idx[:count] = self._order[offset:offset + count]
        valid = np.zeros((self.minibatch_size,), np.float32)
        valid[:count] = 1.0

        self.minibatch_class = cls
        self.minibatch_indices = idx
        self.minibatch_valid = valid
        self.minibatch_offset = offset + count

        if self.minibatch_offset >= end_of_class:
            self.class_ended <<= True
        if self.minibatch_offset >= self.total_samples:
            self.last_minibatch <<= True
            self.epoch_ended <<= True
            self.epoch_number += 1
            self.minibatch_offset = 0
            self._reset_order()
        self.event("minibatch", "single", cls=CLASS_NAMES[cls],
                   offset=offset, count=count)

    # -- snapshot state (ref loader position pickled into snapshots) --------
    @property
    def state(self):
        pool = getattr(self, "_train_pool", None)
        return {"epoch_number": self.epoch_number,
                "minibatch_offset": self.minibatch_offset,
                # the GLOBAL minibatch the offsets/order were walked
                # with: reshard-on-restore (snapshotter.reshard_state)
                # proves a resized mesh can serve the same data order
                # by checking the new data axis still divides it
                "minibatch_size": int(self.minibatch_size),
                "order": None if self._order is None else self._order.copy(),
                # self-contained exactness: the shuffle stream's
                # (seed, counter) words and the ensemble subset pool
                # ride along, so a restored loader replays the exact
                # reshuffle sequence even when the global PRNG registry
                # is restored separately (or not at all — unit-level
                # restores, cross-process verifiers)
                "prng": dict(self.prng.state),
                "train_pool": None if pool is None else pool.copy()}

    @state.setter
    def state(self, st):
        self.epoch_number = st["epoch_number"]
        self.minibatch_offset = st["minibatch_offset"]
        if st["order"] is not None:
            self._order = st["order"].copy()
        if st.get("prng") is not None:      # absent in legacy snapshots
            self.prng.state = st["prng"]
        if st.get("train_pool") is not None:
            self._train_pool = st["train_pool"].copy()

    def get_metric_values(self):
        out = {"epochs": self.epoch_number}
        if getattr(self, "labels_mapping", None):
            out["labels"] = {
                "n_classes": len(self.labels_mapping),
                "distribution": self.label_distribution,
            }
        return out
