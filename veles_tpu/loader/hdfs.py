"""HDFS text loader (ref veles/loader/hdfs_loader.py:48 — numeric text
rows streamed from a Hadoop filesystem).

Built on ``pyarrow.fs``: an ``hdfs://host:port/path`` URI uses
HadoopFileSystem (needs libhdfs at runtime — gated with a clear error),
anything else resolves through LocalFileSystem, which keeps the parsing
and batching logic fully testable offline.  Rows are
``v1<sep>v2<sep>...<sep>label`` with the label column optional."""

import os

import numpy as np

from veles_tpu.loader.base import TEST, TRAIN, VALID
from veles_tpu.loader.fullbatch import FullBatchLoader

CLASS_KEYS = {"test": TEST, "validation": VALID, "train": TRAIN}


def open_fs(uri):
    """→ (pyarrow FileSystem, path)."""
    from pyarrow import fs
    if uri.startswith("hdfs://"):
        rest = uri[len("hdfs://"):]
        host, _, path = rest.partition("/")
        hostname, _, port = host.partition(":")
        try:
            return (fs.HadoopFileSystem(hostname,
                                        int(port) if port else 8020),
                    "/" + path)
        except Exception as e:  # noqa: BLE001 — no libhdfs in this image
            raise RuntimeError(
                "hdfs:// needs libhdfs available to pyarrow "
                "(HadoopFileSystem): %s" % e) from e
    if uri.startswith("file://"):
        uri = uri[len("file://"):]
    return fs.LocalFileSystem(), os.path.abspath(uri)


def read_rows(uri, separator=None, labeled=True):
    """Parse one text file → (data [N, F] float32, labels [N] or None)."""
    filesystem, path = open_fs(uri)
    with filesystem.open_input_stream(path) as f:
        text = f.read().decode()
    data, labels = [], []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split(separator) if separator else line.replace(
            ",", " ").split()
        values = [float(p) for p in parts]
        if labeled:
            labels.append(int(values[-1]))
            values = values[:-1]
        data.append(values)
    if not data:
        raise ValueError("no rows in %s" % uri)
    return (np.asarray(data, np.float32),
            np.asarray(labels, np.int32) if labeled else None)


class HDFSTextLoader(FullBatchLoader):
    """:param files: {class_name: uri} (class_name in
    test/validation/train)."""

    MAPPING = "hdfs_text"

    def __init__(self, workflow, files=None, separator=None, labeled=True,
                 **kwargs):
        super(HDFSTextLoader, self).__init__(workflow, **kwargs)
        self.files = files or {}
        self.separator = separator
        self.labeled = labeled

    def load_data(self):
        datas = [None, None, None]
        labels = [None, None, None]
        lengths = [0, 0, 0]
        for key, uri in self.files.items():
            cls = CLASS_KEYS[key]
            d, l = read_rows(uri, self.separator, self.labeled)
            datas[cls], labels[cls] = d, l
            lengths[cls] = len(d)
        if sum(lengths) == 0:
            raise ValueError("HDFSTextLoader: no files given")
        present = [c for c in (TEST, VALID, TRAIN) if datas[c] is not None]
        self.original_data = np.concatenate([datas[c] for c in present])
        if self.labeled:
            self.original_labels = np.concatenate(
                [labels[c] for c in present])
        self.class_lengths = lengths
