"""Image loaders (ref: veles/loader/{image,file_image,fullbatch_image,
file_loader}.py — PIL decode, crop/scale, color conversion, filesystem
scanning with wildcards and auto-labeling).

All decode/transform work happens on the host at ingest; the resulting
tensor goes to HBM once via FullBatchLoader (ref FullBatchImageLoader,
fullbatch_image.py:56)."""

import glob
import os

import numpy as np

from veles_tpu.loader.base import TEST, TRAIN, VALID
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.normalization import make_normalizer


def decode_image(path, size=None, grayscale=False, crop=None,
                 color_space="RGB", rotation=0.0, mirror=False):
    """Load one image file → float32 HWC array in [0, 1]
    (ref ImageLoader decode/scale/crop/rotation/color conversion,
    loader/image.py:106).

    ``color_space``: "RGB", "HSV", "YCbCr", or "L"; ``rotation`` in
    degrees (bilinear, same canvas); ``mirror`` flips horizontally."""
    from PIL import Image
    img = Image.open(path)
    img = img.convert("L" if grayscale else color_space)
    if crop is not None:
        left, top, w, h = crop
        img = img.crop((left, top, left + w, top + h))
    if rotation:
        img = img.rotate(rotation, Image.BILINEAR)
    if mirror:
        img = img.transpose(Image.FLIP_LEFT_RIGHT)
    if size is not None:
        img = img.resize((size[1], size[0]), Image.BILINEAR)
    arr = np.asarray(img, np.float32) / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def scan_files(patterns, extensions=(".png", ".jpg", ".jpeg", ".bmp",
                                     ".ppm", ".gif")):
    """Expand glob patterns into a sorted file list (ref FileLoader
    wildcard scanning, loader/file_loader.py)."""
    files = []
    for pat in ([patterns] if isinstance(patterns, str) else patterns):
        if os.path.isdir(pat):
            pat = os.path.join(pat, "**", "*")
        # sorted: glob order is filesystem-dependent, and sample
        # order must be bit-identical across hosts/runs (VB1101)
        for f in sorted(glob.glob(pat, recursive=True)):
            if os.path.isfile(f) and f.lower().endswith(extensions):
                files.append(f)
    return sorted(files)


def auto_label(files):
    """Directory-name labeling (ref AutoLabelFileImageLoader,
    file_loader.py:277): label = parent directory name."""
    names = sorted({os.path.basename(os.path.dirname(f)) for f in files})
    index = {n: i for i, n in enumerate(names)}
    labels = np.array([index[os.path.basename(os.path.dirname(f))]
                       for f in files], np.int32)
    return labels, names


class FullBatchImageLoader(FullBatchLoader):
    """Scan + decode + normalize image files into an HBM-resident full
    batch (ref FullBatchImageLoader).

    :param train_paths/valid_paths/test_paths: glob patterns or dirs;
        labels come from parent directory names unless ``labeled=False``.
    """

    MAPPING = "full_batch_image"

    def __init__(self, workflow, train_paths=None, valid_paths=None,
                 test_paths=None, size=(32, 32), grayscale=False,
                 crop=None, color_space="RGB", normalization="none",
                 labeled=True, augment=None, **kwargs):
        super(FullBatchImageLoader, self).__init__(workflow, **kwargs)
        self.paths = {TRAIN: train_paths, VALID: valid_paths,
                      TEST: test_paths}
        self.size = size
        self.grayscale = grayscale
        self.crop = crop
        self.color_space = color_space
        self.labeled = labeled
        #: {"mirror": True, "rotations": [-10, 10]} — each variant ADDS a
        #: copy of the train class (ref the reference's crop/rotation/
        #: mirror augmentation in ImageLoader)
        self.augment = augment or {}
        self.normalizer = make_normalizer(normalization) \
            if isinstance(normalization, str) else normalization
        self.label_names = None

    def _decode(self, path, rotation=0.0, mirror=False):
        return decode_image(path, self.size, self.grayscale, self.crop,
                            self.color_space, rotation, mirror)

    def _variants(self, cls):
        """(rotation, mirror) decode variants for a class — augmentation
        applies to TRAIN only."""
        variants = [(0.0, False)]
        if cls == TRAIN:
            for rot in self.augment.get("rotations", ()):
                variants.append((float(rot), False))
            if self.augment.get("mirror"):
                variants += [(rot, True) for rot, _ in list(variants)]
        return variants

    def _scan_classes(self):
        all_files = {}
        for cls in (TEST, VALID, TRAIN):
            pats = self.paths[cls]
            all_files[cls] = scan_files(pats) if pats else []
        if not any(all_files.values()):
            raise ValueError("no image files matched")
        return all_files

    def _decode_classes(self, all_files, path_map=None):
        """Decode every file per class with its augmentation variants.
        ``path_map`` substitutes a paired file (targets) while keeping the
        exact same ordering/variants as the input pass."""
        ordered = all_files[TEST] + all_files[VALID] + all_files[TRAIN]
        label_of = {}
        if self.labeled:
            base_labels, self.label_names = auto_label(ordered)
            label_of = dict(zip(ordered, base_labels))
        images, labels = [], []
        lengths = [0, 0, 0]
        for cls in (TEST, VALID, TRAIN):
            for rot, mir in self._variants(cls):
                for f in all_files[cls]:
                    src = path_map[f] if path_map is not None else f
                    images.append(self._decode(src, rot, mir))
                    if self.labeled:
                        labels.append(label_of[f])
                lengths[cls] += len(all_files[cls])
        return images, labels, lengths

    def _finalize(self, images, labels, lengths):
        data = np.stack(images)
        self.normalizer.analyze(data)
        data = self.normalizer.normalize(data).reshape(data.shape)
        self._dataset_prenormalized = True   # base must not re-normalize
        self.original_data = data
        self.original_labels = (np.asarray(labels, np.int32)
                                if self.labeled and labels else None)
        self.class_lengths = lengths
        n_classes = (len(self.label_names) if self.label_names
                     else len(set(labels)) if labels else 0)
        self.info("loaded %d images %s, %d classes", len(images),
                  data.shape[1:], n_classes)

    def load_data(self):
        images, labels, lengths = self._decode_classes(self._scan_classes())
        self._finalize(images, labels, lengths)


class FileListImageLoader(FullBatchImageLoader):
    """Images from explicit list files — one ``path label`` pair per line
    (ref FileListImageLoader, loader/file_loader.py).  Paths resolve
    relative to the list file."""

    MAPPING = "file_list_image"

    def __init__(self, workflow, train_list=None, valid_list=None,
                 test_list=None, **kwargs):
        kwargs.setdefault("labeled", True)
        super(FileListImageLoader, self).__init__(workflow, **kwargs)
        self.lists = {TRAIN: train_list, VALID: valid_list,
                      TEST: test_list}

    @staticmethod
    def _read_list(path):
        base = os.path.dirname(os.path.abspath(path))
        entries = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                # "path label" with label an int; a trailing token that is
                # not an int belongs to a filename containing spaces
                parts = line.rsplit(None, 1)
                if len(parts) == 2:
                    try:
                        label = int(parts[1])
                        fname = parts[0]
                    except ValueError:
                        fname, label = line, 0
                else:
                    fname, label = line, 0
                if not os.path.isabs(fname):
                    fname = os.path.join(base, fname)
                entries.append((fname, label))
        return entries

    def load_data(self):
        images, labels = [], []
        lengths = [0, 0, 0]
        for cls in (TEST, VALID, TRAIN):
            if not self.lists[cls]:
                continue
            entries = self._read_list(self.lists[cls])
            for rot, mir in self._variants(cls):
                for fname, label in entries:
                    images.append(self._decode(fname, rot, mir))
                    labels.append(label)
                lengths[cls] += len(entries)
        if not images:
            raise ValueError("no entries in the list files")
        self._finalize(images, labels, lengths)


class ImageMSELoader(FullBatchImageLoader):
    """Paired input/target images for regression/AE training (ref
    loader/image_mse.py).  Inputs pair with targets by basename when that
    is unambiguous (unique on both sides, every input basename present in
    the target scan); otherwise both sides are paired positionally in one
    flat sorted order.  Targets decode with the SAME augmentation
    variants and are normalized by the SAME fitted normalizer, so
    prediction and target live in one value space
    (``original_targets``, loss="mse")."""

    MAPPING = "image_mse"

    def __init__(self, workflow, target_paths=None, **kwargs):
        kwargs.setdefault("labeled", False)
        super(ImageMSELoader, self).__init__(workflow, **kwargs)
        if not target_paths:
            raise ValueError("ImageMSELoader needs target_paths=")
        self.target_paths = target_paths

    def load_data(self):
        files = self._scan_classes()
        inputs_flat = files[TEST] + files[VALID] + files[TRAIN]
        target_files = scan_files(self.target_paths)
        if len(target_files) != len(inputs_flat):
            raise ValueError(
                "%d target files cannot pair %d input files 1:1"
                % (len(target_files), len(inputs_flat)))
        # Pair by basename when unambiguous; otherwise pair both sides in
        # one flat sorted order (inputs_flat is grouped per class split,
        # whose concatenation need not match the flat sorted target scan).
        by_name = {}
        for t in target_files:
            by_name.setdefault(os.path.basename(t), []).append(t)
        input_names = [os.path.basename(f) for f in inputs_flat]
        if (all(len(v) == 1 for v in by_name.values())
                and len(set(input_names)) == len(input_names)
                and all(n in by_name for n in input_names)):
            path_map = {f: by_name[os.path.basename(f)][0]
                        for f in inputs_flat}
        else:
            path_map = dict(zip(sorted(inputs_flat), target_files))
        images, labels, lengths = self._decode_classes(files)
        self._finalize(images, labels, lengths)
        t_images, _, _ = self._decode_classes(files, path_map=path_map)
        targets = np.stack(t_images)
        self.original_targets = self.normalizer.normalize(
            targets).reshape(targets.shape)
