"""Image loaders (ref: veles/loader/{image,file_image,fullbatch_image,
file_loader}.py — PIL decode, crop/scale, color conversion, filesystem
scanning with wildcards and auto-labeling).

All decode/transform work happens on the host at ingest; the resulting
tensor goes to HBM once via FullBatchLoader (ref FullBatchImageLoader,
fullbatch_image.py:56)."""

import glob
import os

import numpy as np

from veles_tpu.loader.base import TEST, TRAIN, VALID
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.normalization import make_normalizer


def decode_image(path, size=None, grayscale=False, crop=None):
    """Load one image file → float32 HWC array in [0, 1]
    (ref ImageLoader decode/scale/crop, loader/image.py:106)."""
    from PIL import Image
    img = Image.open(path)
    img = img.convert("L" if grayscale else "RGB")
    if crop is not None:
        left, top, w, h = crop
        img = img.crop((left, top, left + w, top + h))
    if size is not None:
        img = img.resize((size[1], size[0]), Image.BILINEAR)
    arr = np.asarray(img, np.float32) / 255.0
    if grayscale:
        arr = arr[:, :, None]
    return arr


def scan_files(patterns, extensions=(".png", ".jpg", ".jpeg", ".bmp",
                                     ".ppm", ".gif")):
    """Expand glob patterns into a sorted file list (ref FileLoader
    wildcard scanning, loader/file_loader.py)."""
    files = []
    for pat in ([patterns] if isinstance(patterns, str) else patterns):
        if os.path.isdir(pat):
            pat = os.path.join(pat, "**", "*")
        for f in glob.glob(pat, recursive=True):
            if os.path.isfile(f) and f.lower().endswith(extensions):
                files.append(f)
    return sorted(files)


def auto_label(files):
    """Directory-name labeling (ref AutoLabelFileImageLoader,
    file_loader.py:277): label = parent directory name."""
    names = sorted({os.path.basename(os.path.dirname(f)) for f in files})
    index = {n: i for i, n in enumerate(names)}
    labels = np.array([index[os.path.basename(os.path.dirname(f))]
                       for f in files], np.int32)
    return labels, names


class FullBatchImageLoader(FullBatchLoader):
    """Scan + decode + normalize image files into an HBM-resident full
    batch (ref FullBatchImageLoader).

    :param train_paths/valid_paths/test_paths: glob patterns or dirs;
        labels come from parent directory names unless ``labeled=False``.
    """

    MAPPING = "full_batch_image"

    def __init__(self, workflow, train_paths=None, valid_paths=None,
                 test_paths=None, size=(32, 32), grayscale=False,
                 crop=None, normalization="none", labeled=True, **kwargs):
        super(FullBatchImageLoader, self).__init__(workflow, **kwargs)
        self.paths = {TRAIN: train_paths, VALID: valid_paths,
                      TEST: test_paths}
        self.size = size
        self.grayscale = grayscale
        self.crop = crop
        self.labeled = labeled
        self.normalizer = make_normalizer(normalization) \
            if isinstance(normalization, str) else normalization
        self.label_names = None

    def load_data(self):
        images, labels = [], []
        lengths = [0, 0, 0]
        all_files = {}
        for cls in (TEST, VALID, TRAIN):
            pats = self.paths[cls]
            all_files[cls] = scan_files(pats) if pats else []
            lengths[cls] = len(all_files[cls])
        ordered = all_files[TEST] + all_files[VALID] + all_files[TRAIN]
        if not ordered:
            raise ValueError("no image files matched")
        if self.labeled:
            labels_arr, self.label_names = auto_label(ordered)
        for f in ordered:
            images.append(decode_image(f, self.size, self.grayscale,
                                       self.crop))
        data = np.stack(images)
        self.normalizer.analyze(data)
        data = self.normalizer.normalize(data).reshape(data.shape)
        self.original_data = data
        self.original_labels = labels_arr if self.labeled else None
        self.class_lengths = lengths
        self.info("loaded %d images %s, %d classes", len(ordered),
                  data.shape[1:],
                  len(self.label_names) if self.labeled else 0)
