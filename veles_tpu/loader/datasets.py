"""Standard-format dataset readers for the BASELINE accuracy gates
(ref: the MNIST/CIFAR workflows the reference publishes results for,
docs/source/manualrst_veles_algorithms.rst:32-52).

Zero-egress friendly: these only *read* the canonical on-disk formats —
MNIST idx(.gz) files and the CIFAR-10 python batches — from the datasets
directory; nothing is downloaded.  ``*_available`` predicates let tests
skip-not-fail when the data is not mounted (VERDICT r1 #10)."""

import gzip
import os
import pickle
import struct

import numpy as np

from veles_tpu.config import root


def datasets_dir():
    return root.common.dirs.get("datasets", "datasets")


# ----------------------------------------------------------------- MNIST
_MNIST_FILES = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte",
                "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")


def _mnist_dir(directory=None):
    return os.path.join(directory or datasets_dir(), "mnist")


def _idx_path(directory, stem):
    for suffix in ("", ".gz"):
        p = os.path.join(directory, stem + suffix)
        if os.path.exists(p):
            return p
    return None


def mnist_available(directory=None):
    d = _mnist_dir(directory)
    return all(_idx_path(d, s) is not None for s in _MNIST_FILES)


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError("not an idx file: %s" % path)
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(shape)


def load_mnist(directory=None):
    """(train_x[60000,784] f32 0..1, train_y, test_x[10000,784], test_y)."""
    d = _mnist_dir(directory)
    out = []
    for stem in _MNIST_FILES:
        path = _idx_path(d, stem)
        if path is None:
            raise FileNotFoundError("missing MNIST file %s under %s"
                                    % (stem, d))
        arr = _read_idx(path)
        if arr.ndim == 3:
            arr = arr.reshape(len(arr), -1).astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.int32)
        out.append(arr)
    return tuple(out)


# --------------------------------------------------------------- CIFAR-10
def _cifar_dir(directory=None):
    return os.path.join(directory or datasets_dir(), "cifar-10-batches-py")


def cifar10_available(directory=None):
    d = _cifar_dir(directory)
    return (os.path.exists(os.path.join(d, "data_batch_1"))
            and os.path.exists(os.path.join(d, "test_batch")))


def load_cifar10(directory=None):
    """(train_x[50000,32,32,3] f32 0..1, train_y, test_x, test_y)."""
    d = _cifar_dir(directory)

    def read(name):
        with open(os.path.join(d, name), "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        x = batch[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return (x.astype(np.float32) / 255.0,
                np.asarray(batch[b"labels"], np.int32))

    xs, ys = zip(*(read("data_batch_%d" % i) for i in range(1, 6)))
    test_x, test_y = read("test_batch")
    return (np.concatenate(xs), np.concatenate(ys), test_x, test_y)


# ---------------------------------------------------------------- STL-10
_STL_FILES = ("train_X.bin", "train_y.bin", "test_X.bin", "test_y.bin")


def _stl_dir(directory=None):
    return os.path.join(directory or datasets_dir(), "stl10_binary")


def stl10_available(directory=None):
    d = _stl_dir(directory)
    return all(os.path.exists(os.path.join(d, f)) for f in _STL_FILES)


def load_stl10(directory=None):
    """(train_x[5000,96,96,3] f32 0..1, train_y 0-based, test_x, test_y).

    The binary format stores each image as 3x96x96 **column-major**
    (channels, then column-major pixels); labels are 1..10."""
    d = _stl_dir(directory)

    def read_x(name):
        raw = np.fromfile(os.path.join(d, name), np.uint8)
        x = raw.reshape(-1, 3, 96, 96)          # [N, C, cols, rows]
        return (x.transpose(0, 3, 2, 1)          # → [N, rows, cols, C]
                .astype(np.float32) / 255.0)

    def read_y(name):
        return np.fromfile(os.path.join(d, name),
                           np.uint8).astype(np.int32) - 1

    return (read_x("train_X.bin"), read_y("train_y.bin"),
            read_x("test_X.bin"), read_y("test_y.bin"))
