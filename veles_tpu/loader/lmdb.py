"""LMDB key-value dataset loader (ref Znicz loader_lmdb.LMDBLoader,
referenced by manualrst_veles_workflow_creation.rst:99 — the Caffe-style
LMDB image database).

The real ``lmdb`` package is optional (not in this image); the loader is
written against the tiny subset of its API it needs (env.begin() →
txn.cursor() iteration, txn.get), with an injectable ``env_factory`` so the
logic is testable without the C library.  Records are either raw float32
tensors, ``.npy`` blobs, or ``(data, label)`` pickles."""

import io
import pickle

import numpy as np

from veles_tpu.loader.base import TEST, TRAIN, VALID
from veles_tpu.loader.fullbatch import FullBatchLoader

CLASS_KEYS = {"test": TEST, "validation": VALID, "train": TRAIN}


def _default_env_factory(path):
    try:
        import lmdb
    except ImportError as e:
        raise ImportError(
            "LMDBLoader needs the 'lmdb' package (not bundled in this "
            "image); pass env_factory= to use another KV store") from e
    return lmdb.open(path, readonly=True, lock=False)


def decode_record(value, sample_shape=None):
    """value bytes → (np.ndarray data, label or None)."""
    if value[:6] == b"\x93NUMPY":
        return np.load(io.BytesIO(value)), None
    if value[:2] in (b"\x80\x02", b"\x80\x03", b"\x80\x04", b"\x80\x05"):
        obj = pickle.loads(value)
        if isinstance(obj, tuple):
            return np.asarray(obj[0], np.float32), obj[1]
        return np.asarray(obj, np.float32), None
    data = np.frombuffer(value, np.float32)
    if sample_shape:
        data = data.reshape(sample_shape)
    return data, None


class LMDBLoader(FullBatchLoader):
    """:param dbs: {class_name: lmdb_path} (class_name in
    test/validation/train); loads every record into the full-batch arrays.
    """

    MAPPING = "lmdb"

    def __init__(self, workflow, dbs=None, sample_shape=None,
                 env_factory=None, **kwargs):
        super(LMDBLoader, self).__init__(workflow, **kwargs)
        self.dbs = dbs or {}
        self.sample_shape = sample_shape
        self.env_factory = env_factory or _default_env_factory

    def _read_db(self, path):
        env = self.env_factory(path)
        datas, labels = [], []
        try:
            with env.begin() as txn:
                cur = txn.cursor()
                for _key, value in cur:
                    d, l = decode_record(value, self.sample_shape)
                    datas.append(np.asarray(d, np.float32))
                    labels.append(-1 if l is None else int(l))
        finally:
            env.close()
        return datas, labels

    def load_data(self):
        per_class = {TEST: ([], []), VALID: ([], []), TRAIN: ([], [])}
        for key, path in self.dbs.items():
            cls = CLASS_KEYS[key]
            d, l = self._read_db(path)
            per_class[cls][0].extend(d)
            per_class[cls][1].extend(l)
        lengths = [len(per_class[c][0]) for c in (TEST, VALID, TRAIN)]
        if sum(lengths) == 0:
            raise ValueError("LMDBLoader: no records in %s" % (self.dbs,))
        datas = [np.stack(per_class[c][0]) if per_class[c][0] else None
                 for c in (TEST, VALID, TRAIN)]
        all_labels = sum((per_class[c][1] for c in (TEST, VALID, TRAIN)), [])
        self.original_data = np.concatenate(
            [d for d in datas if d is not None])
        n_labeled = sum(1 for l in all_labels if l >= 0)
        if n_labeled == 0:
            self.original_labels = None
        elif n_labeled < len(all_labels):
            raise ValueError(
                "LMDBLoader: %d of %d records carry labels — mixing "
                "labeled and unlabeled records would silently train on "
                "wrong labels" % (n_labeled, len(all_labels)))
        else:
            self.original_labels = np.asarray(all_labels, np.int32)
        self.class_lengths = lengths
