"""Data pipeline (ref: veles/loader/ — SURVEY.md §2.5)."""

from veles_tpu.loader.base import (CLASS_NAMES, TEST, TRAIN, VALID, Loader)
from veles_tpu.loader.fullbatch import FullBatchLoader

__all__ = ["Loader", "FullBatchLoader", "TEST", "VALID", "TRAIN",
           "CLASS_NAMES"]
