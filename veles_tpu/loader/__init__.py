"""Data pipeline (ref: veles/loader/ — SURVEY.md §2.5)."""

from veles_tpu.loader.audio import AudioLoader
from veles_tpu.loader.base import (CLASS_NAMES, TEST, TRAIN, VALID, Loader)
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.lmdb import LMDBLoader

__all__ = ["Loader", "FullBatchLoader", "AudioLoader", "LMDBLoader",
           "TEST", "VALID", "TRAIN", "CLASS_NAMES"]
