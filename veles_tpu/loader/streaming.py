"""Streaming ingestion loaders (ref: veles/zmq_loader.py:74,
loader/interactive.py:57, downloader.py:56).

* ZeroMQLoader — samples pushed over a ZeroMQ PULL socket into a queue
* InteractiveLoader — ``feed()`` samples from a REPL / calling thread
* Downloader — fetch + unpack a dataset archive into the data dir
  (gracefully reports when the environment has no egress)."""

import os
import queue
import tarfile
import threading
import zipfile

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.base import TEST, Loader
from veles_tpu.units import Unit


class QueueLoaderBase(Loader):
    """Serves whatever samples appear on an in-process queue; each run()
    takes up to ``minibatch_size`` pending samples (fixed shape, padded
    with the validity mask like every loader here)."""

    carries_data = True

    def __init__(self, workflow, sample_shape=None, queue_size=4096,
                 drain_timeout=0.05, **kwargs):
        super(QueueLoaderBase, self).__init__(workflow, **kwargs)
        if sample_shape is None:
            raise ValueError("%s needs sample_shape" % type(self).__name__)
        self.sample_shape = tuple(sample_shape)
        self.queue = queue.Queue(queue_size)
        self.drain_timeout = drain_timeout
        self.stopped_streaming = False

    def load_data(self):
        # streaming: no fixed dataset; everything is "test" class served
        # on demand (ref ZeroMQLoader semantics).  class_lengths carries
        # minibatch_size so the base class doesn't clamp it down.
        self.class_lengths = [self.minibatch_size, 0, 0]
        self.shuffle_enabled = False

    def feed(self, sample):
        self.queue.put(np.asarray(sample, np.float32))

    def run(self):
        data = np.zeros((self.minibatch_size,) + self.sample_shape,
                        np.float32)
        valid = np.zeros((self.minibatch_size,), np.float32)
        got = 0
        timeout = 30   # wait for at least one sample; after that only
        while got < self.minibatch_size:   # drain in-flight deliveries
            try:
                item = self.queue.get(block=True, timeout=timeout)
            except queue.Empty:
                break
            if item is None:   # poison pill = end of stream
                self.stopped_streaming = True
                break
            data[got] = item
            valid[got] = 1.0
            got += 1
            timeout = self.drain_timeout
        self.minibatch_data = data
        self.minibatch_valid = valid
        self.minibatch_class = TEST
        self.minibatch_indices = None


class InteractiveLoader(QueueLoaderBase):
    """feed() from the REPL (ref loader/interactive.py:57)."""

    MAPPING = "interactive"


class ZeroMQLoader(QueueLoaderBase):
    """Receives pickled numpy samples on a ZeroMQ PULL socket
    (ref veles/zmq_loader.py:74 — the reference's streaming ingestion)."""

    MAPPING = "zeromq"

    def __init__(self, workflow, endpoint="tcp://127.0.0.1:0", **kwargs):
        super(ZeroMQLoader, self).__init__(workflow, **kwargs)
        self.endpoint = endpoint
        self._thread = None
        self._ctx = None

    def initialize(self, **kwargs):
        super(ZeroMQLoader, self).initialize(**kwargs)
        import zmq
        self._ctx = zmq.Context.instance()
        sock = self._ctx.socket(zmq.PULL)
        if self.endpoint.endswith(":0"):
            port = sock.bind_to_random_port(self.endpoint[:-2])
            self.endpoint = "%s:%d" % (self.endpoint[:-2], port)
        else:
            sock.bind(self.endpoint)
        self.info("ZeroMQLoader listening on %s", self.endpoint)

        def pump():
            while True:
                try:
                    obj = sock.recv_pyobj()
                except Exception:  # noqa: BLE001 — context shut down
                    break
                self.queue.put(None if obj is None
                               else np.asarray(obj, np.float32))
                if obj is None:
                    break

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()


class GeneratorLoader(Loader):
    """Host-side input pipeline for datasets too big for HBM *or* host RAM
    (the ImageNet path — SURVEY §7 step 6 'host-side decode with
    double-buffered device puts').  Pulls fixed-shape minibatches from a
    user callable ``generator(step, size) -> (data, labels)`` (labels
    optional); decode/augment happens in the callable on the host, and
    because the trainer's dispatch is fully async, producing batch t+1
    overlaps the device computing step t.  Epochs are synthetic:
    ``steps_per_epoch`` minibatches = one TRAIN epoch (drives the Decision
    gates exactly like an index loader)."""

    MAPPING = "generator"
    carries_data = True

    def __init__(self, workflow, generator=None, sample_shape=None,
                 steps_per_epoch=100, prefetch=0, **kwargs):
        super(GeneratorLoader, self).__init__(workflow, **kwargs)
        if generator is None or sample_shape is None:
            raise ValueError("GeneratorLoader needs generator= and "
                             "sample_shape=")
        self.generator = generator
        self.sample_shape = tuple(sample_shape)
        self.steps_per_epoch = int(steps_per_epoch)
        #: produce up to this many batches ahead on a worker thread —
        #: overlaps host-side decode/augment with the device step (the
        #: async dispatch already overlaps the transfer; this overlaps
        #: the PRODUCTION).  0 = synchronous (default).  The generator
        #: must be thread-compatible (it is called from one worker only).
        self.prefetch = int(prefetch)
        self.minibatch_data = None
        self.minibatch_labels = None
        self.minibatch_targets = None
        self._step = 0
        self._pool_ = None          # underscore suffix: never pickled
        self._pending_ = None

    def load_data(self):
        self.class_lengths = [0, 0,
                              self.steps_per_epoch * self.minibatch_size]
        self.shuffle_enabled = False   # ordering belongs to the generator

    def _produce(self, step):
        out = self.generator(step, self.minibatch_size)
        if isinstance(out, tuple):
            data, labels = (out + (None,))[:2]
        else:
            data, labels = out, None
        data = np.asarray(data, np.float32)
        if data.shape != (self.minibatch_size,) + self.sample_shape:
            raise ValueError("generator returned %s, expected %s"
                             % (data.shape,
                                (self.minibatch_size,) + self.sample_shape))
        return data, (None if labels is None
                      else np.asarray(labels, np.int32))

    def _next_batch(self):
        if self.prefetch <= 0:
            batch = self._produce(self._step)
            self._step += 1
            return batch
        from concurrent.futures import ThreadPoolExecutor
        if self._pool_ is None:
            self._pool_ = ThreadPoolExecutor(
                1, thread_name_prefix="loader-prefetch")
            self._pending_ = []
        while len(self._pending_) < self.prefetch + 1:
            self._pending_.append(
                self._pool_.submit(self._produce, self._step))
            self._step += 1
        return self._pending_.pop(0).result()

    def run(self):
        super(GeneratorLoader, self).run()   # epoch flags / offsets
        self.minibatch_data, self.minibatch_labels = self._next_batch()

    def stop(self):
        """Release the prefetch worker (Workflow.stop calls this) — a
        generator blocked on I/O must not hang interpreter exit.  The
        step counter rolls back over the discarded pending batches so a
        post-stop ``state`` read still reports the CONSUMED position."""
        if self._pool_ is not None:
            self._pool_.shutdown(wait=False, cancel_futures=True)
            self._pool_ = None
            self._step -= len(self._pending_ or [])
            self._pending_ = None
        super(GeneratorLoader, self).stop()

    @property
    def state(self):
        st = super(GeneratorLoader, self).state
        # the resume position is the next UNCONSUMED step — submitted-
        # but-pending prefetch batches are regenerated after restore
        st["generator_step"] = self._step - len(self._pending_ or [])
        return st

    @state.setter
    def state(self, st):
        Loader.state.fset(self, st)
        self._step = st.get("generator_step", 0)
        if self._pending_:
            for fut in self._pending_:
                fut.cancel()
        self._pending_ = [] if self._pool_ is not None else None


class Downloader(Unit):
    """Fetch + unpack a dataset archive into the datasets dir
    (ref veles/downloader.py:56).  In a zero-egress environment the fetch
    fails with a clear message; an already-present file short-circuits."""

    def __init__(self, workflow, url=None, directory=None, **kwargs):
        super(Downloader, self).__init__(workflow, **kwargs)
        self.url = url
        self.directory = directory or root.common.dirs.get(
            "datasets", "datasets")
        self.destination = None

    def initialize(self, **kwargs):
        if not self.url:
            raise ValueError("Downloader needs url=")
        os.makedirs(self.directory, exist_ok=True)
        fname = os.path.join(self.directory, os.path.basename(self.url))
        if not os.path.exists(fname):
            import urllib.request
            self.info("downloading %s", self.url)
            try:
                urllib.request.urlretrieve(self.url, fname)
            except Exception as e:
                raise RuntimeError(
                    "download failed (no network egress?): %s" % e) from e
        self.destination = self._unpack(fname)

    def _unpack(self, fname):
        target = os.path.splitext(fname)[0]
        if fname.endswith(".zip"):
            with zipfile.ZipFile(fname) as zf:
                for member in zf.namelist():   # tar-slip guard
                    dest = os.path.realpath(os.path.join(target, member))
                    if not dest.startswith(os.path.realpath(target)):
                        raise ValueError("archive member escapes target: "
                                         + member)
                zf.extractall(target)
            return target
        if fname.endswith((".tar.gz", ".tgz", ".tar")):
            with tarfile.open(fname) as tf:
                tf.extractall(target, filter="data")
            return target
        return fname
