"""Normalizer registry (ref: veles/normalization.py:110-671).

Stateful two-phase contract kept from the reference: ``analyze(data)``
accumulates dataset statistics, ``normalize(data)`` applies the transform
(and ``denormalize`` inverts it where defined).  State is picklable into
snapshots.  All transforms are pure numpy on the host — normalization
happens once at dataset ingest (the normalized tensor then lives in HBM),
not per minibatch, so there is nothing to stage on device."""

import numpy as np

from veles_tpu.registry import MappedRegistry


class NormalizerRegistry(MappedRegistry):
    """MAPPING name → normalizer class (ref NormalizerRegistry)."""


class NormalizerBase(object, metaclass=NormalizerRegistry):
    mapping = {}

    def __init__(self, **kwargs):
        self.kwargs = kwargs

    def analyze(self, data):
        """Accumulate statistics over (a chunk of) the dataset."""

    def normalize(self, data):
        """In-place-style transform; returns the normalized array."""
        raise NotImplementedError

    def denormalize(self, data):
        raise NotImplementedError(
            "%s cannot denormalize" % type(self).__name__)

    @property
    def state(self):
        return {k: v for k, v in self.__dict__.items() if k != "kwargs"}

    @state.setter
    def state(self, st):
        self.__dict__.update(st)


class NoneNormalizer(NormalizerBase):
    """Identity (ref 'none')."""

    MAPPING = "none"

    def normalize(self, data):
        return data

    def denormalize(self, data):
        return data


class LinearNormalizer(NormalizerBase):
    """Scale each *sample* into [-1, 1] by its own min/max (ref 'linear')."""

    MAPPING = "linear"

    def normalize(self, data):
        flat = data.reshape(len(data), -1)
        mn = flat.min(axis=1, keepdims=True)
        mx = flat.max(axis=1, keepdims=True)
        span = np.where(mx > mn, mx - mn, 1.0)
        out = (flat - mn) * (2.0 / span) - 1.0
        return out.reshape(data.shape).astype(np.float32)


class RangeLinearNormalizer(NormalizerBase):
    """Map a fixed source interval to a fixed destination interval
    (ref 'range_linear'; defaults map [0, 255] → [-1, 1])."""

    MAPPING = "range_linear"

    def __init__(self, source_range=(0.0, 255.0), target_range=(-1.0, 1.0),
                 **kwargs):
        super(RangeLinearNormalizer, self).__init__(**kwargs)
        self.source_range = tuple(map(float, source_range))
        self.target_range = tuple(map(float, target_range))

    def normalize(self, data):
        s0, s1 = self.source_range
        t0, t1 = self.target_range
        scale = (t1 - t0) / (s1 - s0)
        return ((data.astype(np.float32) - s0) * scale + t0)

    def denormalize(self, data):
        s0, s1 = self.source_range
        t0, t1 = self.target_range
        scale = (s1 - s0) / (t1 - t0)
        return (data.astype(np.float32) - t0) * scale + s0


class ExpNormalizer(NormalizerBase):
    """tanh-of-exp squashing (ref 'exp'): 2/(1+exp(-x)) - 1."""

    MAPPING = "exp"

    def normalize(self, data):
        x = data.astype(np.float32)
        return (2.0 / (1.0 + np.exp(-x)) - 1.0)


class MeanDispNormalizer(NormalizerBase):
    """(x - mean) * reciprocal-dispersion, computed over the analyzed data
    per feature (ref 'mean_disp' + veles/mean_disp_normalizer.py — the
    accelerated unit collapses to one vectorized expression)."""

    MAPPING = "mean_disp"

    def __init__(self, **kwargs):
        super(MeanDispNormalizer, self).__init__(**kwargs)
        self._sum = None
        self._min = None
        self._max = None
        self._count = 0

    def analyze(self, data):
        flat = data.reshape(len(data), -1).astype(np.float64)
        if self._sum is None:
            self._sum = flat.sum(axis=0)
            self._min = flat.min(axis=0)
            self._max = flat.max(axis=0)
        else:
            self._sum += flat.sum(axis=0)
            self._min = np.minimum(self._min, flat.min(axis=0))
            self._max = np.maximum(self._max, flat.max(axis=0))
        self._count += len(flat)

    def normalize(self, data):
        if not self._count:
            self.analyze(data)
        mean = (self._sum / self._count).astype(np.float32)
        disp = (self._max - self._min).astype(np.float32)
        rdisp = np.where(disp > 0, 1.0 / np.where(disp > 0, disp, 1.0), 1.0)
        flat = data.reshape(len(data), -1).astype(np.float32)
        return ((flat - mean) * rdisp).reshape(data.shape)


class ExternalMeanNormalizer(NormalizerBase):
    """Subtract a supplied mean array (ref 'external_mean' — e.g. the
    ImageNet channel mean file)."""

    MAPPING = "external_mean"

    def __init__(self, mean_source=None, **kwargs):
        super(ExternalMeanNormalizer, self).__init__(**kwargs)
        if mean_source is None:
            raise ValueError("external_mean needs mean_source=array|path")
        if isinstance(mean_source, str):
            mean_source = np.load(mean_source)
        self.mean = np.asarray(mean_source, np.float32)

    def normalize(self, data):
        return data.astype(np.float32) - self.mean


class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear map fitted on analyzed data so each feature spans
    [-1, 1] (ref 'pointwise')."""

    MAPPING = "pointwise"

    def __init__(self, **kwargs):
        super(PointwiseNormalizer, self).__init__(**kwargs)
        self._min = None
        self._max = None

    def analyze(self, data):
        flat = data.reshape(len(data), -1)
        if self._min is None:
            self._min = flat.min(axis=0).astype(np.float64)
            self._max = flat.max(axis=0).astype(np.float64)
        else:
            self._min = np.minimum(self._min, flat.min(axis=0))
            self._max = np.maximum(self._max, flat.max(axis=0))

    def normalize(self, data):
        if self._min is None:
            self.analyze(data)
        span = np.where(self._max > self._min, self._max - self._min, 1.0)
        flat = data.reshape(len(data), -1).astype(np.float32)
        out = (flat - self._min) * (2.0 / span) - 1.0
        return out.astype(np.float32).reshape(data.shape)


def make_normalizer(name, **kwargs):
    return NormalizerBase.mapping[name](**kwargs)
