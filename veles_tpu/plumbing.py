"""Plumbing units closing and terminating the control graph
(ref: veles/plumbing.py:17-92)."""

from veles_tpu.mutable import Bool
from veles_tpu.units import Unit


class StartPoint(Unit):
    """Workflow entry node (ref plumbing.py:44)."""

    def run(self):
        pass


class EndPoint(Unit):
    """Workflow exit node — running it finishes the workflow
    (ref plumbing.py:60)."""

    def run(self):
        self.workflow.on_workflow_finished()


class Repeater(Unit):
    """Loop closer: fires as soon as any predecessor fires
    (``ignores_gate``), re-entering the hot loop (ref plumbing.py:17)."""

    def __init__(self, workflow, **kwargs):
        super(Repeater, self).__init__(workflow, **kwargs)
        self.ignores_gate = Bool(True)


class FireStarter(Unit):
    """Resets the ``stopped`` flag of attached units so a finished workflow
    segment can run again (ref plumbing.py:92)."""

    def __init__(self, workflow, **kwargs):
        super(FireStarter, self).__init__(workflow, **kwargs)
        self.units_to_fire = []

    def run(self):
        for unit in self.units_to_fire:
            stopped = getattr(unit, "stopped", None)
            if isinstance(stopped, Bool):
                stopped.set(False)
            elif stopped is not None:
                unit.stopped = False
