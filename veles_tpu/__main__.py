"""CLI entry point (ref: veles/__main__.py — `python -m veles_tpu
workflow.py [config.py]`).

Keeps the reference's module contract (__main__.py:799-818): the workflow
file defines ``run(load, main)`` and calls
``load(WorkflowClass, **kwargs)`` to construct (or snapshot-resume) the
workflow, then ``main(**kwargs)`` to initialize + run it.  Config files
are Python executed with ``root`` in scope, mutating the global config
tree (ref _apply_config, __main__.py:426-472); ``--config-list`` inline
statements layer on top."""

import argparse
import json
import runpy
import sys

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.logger import setup_logging


def _death_probability(value):
    """argparse type for --death-probability: [0, 1).  P >= 1 would
    crash before the first unit ever runs — the supervisor drill would
    spin forever with zero progress; negative P silently disables it."""
    p = float(value)
    if not 0.0 <= p < 1.0:
        raise argparse.ArgumentTypeError(
            "death probability must be in [0, 1) — P >= 1 dies before "
            "any unit runs, so a restarting supervisor never progresses")
    return p


class Main(object):
    def __init__(self, argv=None):
        self.argv = argv if argv is not None else sys.argv[1:]
        self.workflow = None
        self._interactive_session = None

    def parse(self):
        return self._build_parser().parse_args(self.argv)

    def _build_parser(self):
        """The full CLI parser (also consumed by
        scripts.generate_frontend to emit the HTML command composer)."""
        p = argparse.ArgumentParser(
            prog="veles_tpu",
            description="TPU-native deep-learning platform")
        p.add_argument("workflow", help="workflow .py file defining "
                       "run(load, main)")
        p.add_argument("config", nargs="?", help="config .py file "
                       "executed with `root` in scope")
        p.add_argument("--config-list", nargs="*", default=[],
                       help="inline config statements, e.g. "
                       "'root.mnist.lr=0.1'")
        p.add_argument("--random-seed", type=int, default=None)
        p.add_argument("--snapshot", default=None,
                       help="resume from a snapshot file, or 'auto' to "
                       "resolve <workflow>_current in the snapshot dir "
                       "(fresh start when absent — the restart-on-failure "
                       "idiom; ref _current symlink snapshotter.py:397-409)")
        p.add_argument("--warm-start", default=None, metavar="SNAPSHOT",
                       help="fine-tuning initializer: copy params whose "
                       "layer/param names and shapes match this snapshot "
                       "(architecture changes tolerated — mismatches stay "
                       "freshly initialized; optimizer/loader/PRNG state "
                       "is NOT restored). Composes with --snapshot auto: "
                       "a found checkpoint wins, so preemption restarts "
                       "keep fine-tuning progress")
        p.add_argument("--snapshot-every", type=int, default=None,
                       metavar="N", help="checkpoint every N epochs "
                       "(injects a snapshotter into StandardWorkflow runs; "
                       "pairs with --snapshot auto for preemption-safe "
                       "training)")
        p.add_argument("--supervise", action="store_true",
                       help="run the training command under the respawn "
                       "supervisor (services.supervisor): the run "
                       "executes as a child process that is respawned "
                       "after SIGKILL/SIGTERM/crashes with exponential "
                       "backoff and crash-loop detection, resuming via "
                       "--snapshot auto (added if absent) — the paper's "
                       "Launcher role for single-host training "
                       "(docs/distributed_training.md)")
        p.add_argument("--allow-remote-snapshot", action="store_true",
                       help="opt in to importing --snapshot from an "
                       "http(s) URL (pickle import runs code)")
        p.add_argument("--snapshot-sha256", default=None,
                       help="expected sha256 of a remote --snapshot")
        p.add_argument("--test", action="store_true",
                       help="skip training; run forward on the loader's "
                       "test/validation set")
        p.add_argument("--lint", action="store_true",
                       help="build the workflow, run the static "
                       "analyzers (veles_tpu.analysis: graph linter + "
                       "jit-staging auditor; with --mesh also the "
                       "VS2xx/VM3xx sharding & memory audit, which "
                       "initializes the workflow on a virtual CPU "
                       "mesh) and exit non-zero on error findings — "
                       "no training, no compute dispatch")
        p.add_argument("--numerics", action="store_true",
                       help="with --lint: initialize the workflow "
                       "(params allocate, no step dispatches) so the "
                       "VN4xx/VR5xx numerics & determinism audit can "
                       "trace the real staged train step; composes "
                       "with --mesh")
        p.add_argument("--serve-max-len", type=int, default=16,
                       metavar="T",
                       help="with --lint --serve: sequence budget the "
                       "audited generators are built with (default "
                       "16)")
        p.add_argument("--concurrency", action="store_true",
                       help="with --lint: add the VT8xx concurrency "
                       "lint (pure AST scan of veles_tpu/services)")
        p.add_argument("--all", action="store_true", dest="lint_all",
                       help="with --lint: add every registered AST "
                       "family (VT8xx concurrency, VW9xx protocol, "
                       "VC95x config/telemetry, VK10xx serialized "
                       "state, VB11xx host determinism) to the "
                       "workflow families — one merged report, one "
                       "exit gate (identical to veles-tpu-lint --all)")
        p.add_argument("--vmem-kib", type=float, default=None,
                       metavar="KiB",
                       help="with --lint: per-core VMEM budget for the "
                       "VP602 Pallas kernel-footprint rule (default "
                       "16384, ~16 MiB)")
        p.add_argument("--fail-on", choices=("error", "warning"),
                       default="error", metavar="{error,warning}",
                       help="with --lint: severity threshold for the "
                       "non-zero exit (exit 0 = below threshold, 1 = "
                       "reached — identical semantics to "
                       "veles-tpu-lint)")
        p.add_argument("--result-file", default=None,
                       help="write gather_results() JSON here")
        p.add_argument("--export-dtype", default="float32",
                       choices=("float32", "float16", "int8"),
                       help="weight storage dtype for --export "
                       "(float16 halves the package, int8 quarters it "
                       "with per-channel scales; the native runtime "
                       "widens to f32 on load)")
        p.add_argument("--export", default=None,
                       help="export trained model package to this path")
        p.add_argument("--export-stablehlo", default=None, metavar="PATH",
                       help="export the jitted forward as a portable "
                       "StableHLO artifact (+params) runnable on any "
                       "XLA backend without the model code")
        p.add_argument("--export-lora", default=None, metavar="PATH",
                       help="export ONLY the LoRA adapters as a small "
                       "package (adapters.npz + base-model sha256 "
                       "lineage) — a rank-8 fine-tune of a 124M base "
                       "ships ~MBs instead of the full model")
        p.add_argument("--serve", type=int, nargs="?", const=-1,
                       default=None, metavar="PORT",
                       help="after training, serve the model over REST;"
                       " with --lint (port optional): run the VD7xx "
                       "decode-path audit over the serving engine's "
                       "tick + prefill pass instead — abstract traces "
                       "only, nothing serves")
        p.add_argument("--generate", default=None,
                       metavar="PROMPT[:MAX_NEW]",
                       help="after training a causal LM, greedily decode "
                       "MAX_NEW (default 32) byte tokens from PROMPT and "
                       "print the result")
        p.add_argument("--web-status", type=int, default=None,
                       metavar="PORT", help="launch the status dashboard")
        p.add_argument("--backend", default=None,
                       help="cpu|tpu|<platform> override")
        p.add_argument("--mesh", default=None, metavar="AXES",
                       help="device mesh for SPMD training, e.g. "
                       "'data=4,model=2' (-1 = all remaining devices); "
                       "ref launcher node specs -n host/0:0x3")
        p.add_argument("--fsdp", action="store_true",
                       help="fully shard parameters and optimizer state "
                       "over the data axis (ZeRO-3 style; composes with "
                       "--mesh model= tensor parallelism)")
        p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                       help="jax.distributed coordinator address "
                       "(multi-host SPMD; ref master -l flag)")
        p.add_argument("--num-processes", type=int, default=None,
                       help="total processes in the multi-host job")
        p.add_argument("--process-id", type=int, default=None,
                       help="this process's index (ref slave -m identity)")
        p.add_argument("--optimize", default=None, metavar="SIZE[:GENS]",
                       help="genetic hyperparameter search over Range() "
                       "config leaves: population SIZE, GENS generations "
                       "(ref veles --optimize, __main__.py:334-345)")
        p.add_argument("--optimize-workers", default="1",
                       help="concurrent fitness evaluations (each is its "
                       "own training subprocess; >1 pins children to "
                       "cpu).  'N@HOST:PORT' additionally serves the "
                       "chromosome queue over HTTP so remote "
                       "--optimize-worker processes on other hosts pull "
                       "evaluations (N local evaluators share the queue; "
                       "N=0 = remote-only — the run then waits for "
                       "workers to connect).  Ref: distributed GA "
                       "fitness, genetics/optimization_workflow.py:"
                       "181-216")
        p.add_argument("--optimize-worker", default=None,
                       metavar="HOST:PORT",
                       help="run as a fitness worker: pull chromosome "
                       "jobs from a coordinator's --optimize-workers "
                       "queue, train this workflow locally per job, "
                       "post the fitness back; exits when the "
                       "coordinator finishes (ref: the slave side of "
                       "the GA job protocol)")
        p.add_argument("--optimize-encoding", default="float",
                       choices=("float", "gray"),
                       help="chromosome encoding: float vector or the "
                       "reference's gray-code binary genome")
        p.add_argument("--ensemble-train", default=None, metavar="N:RATIO",
                       help="train N instances on random train subsets of "
                       "RATIO (ref ensemble/model_workflow.py:137)")
        p.add_argument("--ensemble-workers", type=int, default=1,
                       help="concurrent member trainings (>1 pins "
                       "children to cpu)")
        p.add_argument("--ensemble-test", default=None,
                       metavar="RESULTS.json",
                       help="aggregate the members from an "
                       "--ensemble-train results file: mean-probability "
                       "vote on the eval set (ref --ensemble-test)")
        p.add_argument("--interactive", action="store_true",
                       help="drop into an IPython REPL (fallback: "
                       "code.interact) after constructing the workflow; "
                       "training runs in a background scheduler thread "
                       "so the live workflow stays inspectable — "
                       "wf.stop() / status() / weights(layer) from the "
                       "prompt (ref Main(interactive=True), "
                       "veles/__main__.py:380-394 + the reactor thread, "
                       "launcher.py:556-562)")
        p.add_argument("--manhole", default=None, metavar="SOCKET",
                       help="attachable debug REPL on a unix socket "
                       "(`socat - UNIX-CONNECT:SOCKET`; ref the bundled "
                       "manhole, veles/external/)")
        p.add_argument("--log-db", default=None, metavar="SQLITE",
                       help="duplicate every log record into this "
                       "sqlite file keyed by a per-run session id — "
                       "the cross-run log store behind the dashboard's "
                       "/api/logs browser (ref the Mongo log "
                       "duplication, veles/logger.py:292-331)")
        p.add_argument("--event-log", default=None, metavar="PATH",
                       help="append structured trace events as JSONL "
                       "(ref the Mongo event timeline, logger.py:264-289)")
        p.add_argument("--metrics-out", default=None,
                       metavar="FILE.jsonl",
                       help="stream telemetry records (workflow/unit/"
                       "step spans, compile counters, device-memory "
                       "gauges, predicted-vs-measured MFU) to this "
                       "JSON-lines file, with the final metric state "
                       "dumped at exit — summarize with "
                       "veles-tpu-metrics (docs/services.md)")
        p.add_argument("--steps-per-dispatch", type=int, default=None,
                       metavar="K",
                       help="fuse K minibatch steps into one device "
                       "dispatch (lax.scan inside the jitted sweep) — "
                       "amortizes host-to-device dispatch latency for "
                       "small models and remote TPUs; numerically "
                       "identical to per-step execution")
        p.add_argument("--death-probability", type=_death_probability,
                       default=0.0, metavar="P",
                       help="fault injection: per-unit-run probability "
                       "of a sudden checkpoint-less process crash "
                       "(exit 1) — drills the checkpoint-restart "
                       "elasticity path under a restarting supervisor "
                       "(ref --slave-death-probability)")
        p.add_argument("--watchdog", type=float, default=None,
                       metavar="SECONDS",
                       help="arm the hang watchdog: when no unit/step "
                       "progress is observed for this many seconds, "
                       "the flight record + all-thread stacks are "
                       "dumped to an artifacts/crashdump-* directory "
                       "(the run is NOT killed; read dumps with "
                       "veles-tpu-blackbox).  Default: off standalone, "
                       "300 s in spmd mode")
        p.add_argument("--sentinel", choices=("on", "off"), default=None,
                       help="the numeric-fault sentinel "
                       "(services.sentinel; default on): in-jit health "
                       "probes on every staged train step — "
                       "loss/grad-norm finiteness, EWMA loss-spike "
                       "z-score, update-norm explosion — with "
                       "skip-update, automatic rollback to the last "
                       "healthy commit + exact replay, and a "
                       "numerics:<kind> give-up class; 'off' sets "
                       "root.common.sentinel.enabled=False "
                       "(docs/distributed_training.md \"Numeric-fault "
                       "survival\")")
        p.add_argument("--sync-run", action="store_true",
                       help="block on the device after every trainer step "
                       "for honest per-unit timing (ref --sync-run, "
                       "accelerated_units.py:186-193)")
        p.add_argument("--profile", default=None, metavar="DIR",
                       help="capture a jax/xplane profiler trace of the "
                       "run into DIR (view with tensorboard or xprof; "
                       "the TPU equivalent of the reference's per-unit "
                       "timing + event timeline)")
        p.add_argument("--verbose", "-v", action="count", default=0)
        return p

    def run(self):
        args = self.parse()
        if args.warm_start and args.snapshot and args.snapshot != "auto":
            # fail before any side effect (config exec, model build).
            # --snapshot auto DOES compose: a found checkpoint wins so
            # preemption restarts keep fine-tuning progress
            raise SystemExit(
                "--warm-start with an explicit --snapshot path is "
                "ambiguous: exact resume restores everything, "
                "warm-start only matching params (use --snapshot auto "
                "to combine with restart-on-failure)")
        import logging
        setup_logging(logging.DEBUG if args.verbose else logging.INFO)
        if args.supervise:
            # the parent never touches jax/XLA: it only spawns, watches
            # and respawns the real training command
            return self._run_supervised(args)
        backend = args.backend
        if not backend:
            # --backend wins; root.common.engine.backend (seeded from
            # VELES_TPU_BACKEND) is the config-side fallback, "auto"
            # meaning "leave platform selection to jax"
            from veles_tpu.config import root
            knob = str(root.common.engine.get("backend", "auto"))
            backend = None if knob == "auto" else knob
        if backend:
            # BEFORE compile_cache.enable(): its CPU-backend gate reads
            # jax_platforms, and `--backend cpu` without JAX_PLATFORMS
            # in the env would otherwise slip past it
            import jax
            jax.config.update(
                "jax_platforms",
                "cpu" if backend == "cpu" else backend)
        # persistent XLA compilation cache: re-runs of the same workflow
        # (and supervisor restarts after preemption) skip recompilation
        # — the TPU-era analogue of the reference's on-disk kernel cache
        from veles_tpu import compile_cache
        compile_cache.enable()
        if args.metrics_out:
            # before any jit: the compile listeners installed by
            # enable() must see the first compiles of the run
            from veles_tpu import telemetry
            telemetry.registry.open_sink(args.metrics_out,
                                         dump_at_exit=True)
        if not args.backend and args.lint:
            # linting never needs an accelerator (same guard as the
            # standalone veles-tpu-lint): module-level jax use in the
            # workflow file must not lock chips on a shared host.  A
            # --mesh lint additionally needs enough VIRTUAL cpu devices
            # to build the mesh for the sharding/memory audit
            from veles_tpu.analysis.cli import _force_cpu_devices
            _force_cpu_devices(self._parse_mesh(args.mesh)
                               if args.mesh else None)
        if args.random_seed is not None:
            prng.seed_all(args.random_seed)
        self._apply_config(args)
        if args.event_log:
            from veles_tpu.logger import events
            events.open_sink(args.event_log)
        if args.log_db:
            from veles_tpu.logger import duplicate_log_to
            duplicate_log_to(args.log_db)
            root.common.web.log_db = args.log_db
        if args.sync_run:
            root.common.engine.sync_run = True
        if args.watchdog is not None:
            root.common.blackbox.watchdog_seconds = args.watchdog
        if args.sentinel is not None:
            root.common.sentinel.enabled = args.sentinel == "on"
        if args.steps_per_dispatch is not None:
            root.common.engine.steps_per_dispatch = args.steps_per_dispatch

        if args.optimize_worker:
            return self._run_optimize_worker(args)
        if args.optimize:
            return self._run_optimize(args)
        if args.ensemble_train:
            return self._run_ensemble_train(args)

        web = None
        if args.web_status is not None:
            from veles_tpu.services.web_status import WebStatusServer
            web = WebStatusServer(port=args.web_status)
            web.start()
        self._web = web

        wf_globals = runpy.run_path(args.workflow, run_name="__veles__")
        if "run" not in wf_globals:
            raise SystemExit("%s does not define run(load, main)"
                             % args.workflow)

        def load(cls, **kwargs):
            if args.interactive:
                # ref Main(interactive=True): names defined in an
                # enclosing IPython session fill missing constructor
                # kwargs (explicit kwargs win; only names the workflow
                # class actually accepts are considered)
                import inspect
                try:
                    accepted = {k for k in inspect.signature(
                        cls.__init__).parameters if k != "self"}
                except (TypeError, ValueError):
                    accepted = set()
                for k, v in self._get_interactive_locals().items():
                    if k in accepted and k not in kwargs:
                        kwargs[k] = v
            if args.snapshot_every is not None:
                from veles_tpu.models.standard_workflow import \
                    StandardWorkflow
                if isinstance(cls, type) and \
                        issubclass(cls, StandardWorkflow):
                    kwargs.setdefault("snapshotter_config",
                                      {"interval": args.snapshot_every})
            self.workflow = cls(**kwargs)
            if args.lint:
                # static analysis never restores state: a snapshot
                # import is heavy, side-effectful I/O (pickle executes
                # code) that the --lint contract promises not to do
                self._pending_snapshot = None
                self._pending_warm_start = None
                if web is not None:
                    web.register(self.workflow)
                return self.workflow
            snapshot = args.snapshot
            auto = snapshot == "auto"
            if auto:
                snapshot = self._resolve_auto_snapshot(self.workflow)
            self._pending_warm_start = None
            self._pending_snapshot = None
            resume_src, resume_reason = None, "fresh"
            if snapshot:
                from veles_tpu.services.snapshotter import SnapshotterBase
                # initialize first so staged steps exist, then restore.
                # With --warm-start + --snapshot auto, a found
                # checkpoint WINS: the preemption-restart idiom (exit
                # 75 → same command) must keep fine-tuning progress,
                # not re-warm-start from the base snapshot.
                try:
                    self._pending_snapshot = SnapshotterBase.import_(
                        snapshot,
                        allow_remote=args.allow_remote_snapshot,
                        expected_sha256=args.snapshot_sha256)
                    import os as _os
                    resume_src = _os.path.realpath(snapshot)
                    resume_reason = "current" if auto else "explicit"
                except Exception as e:  # noqa: BLE001 — see below
                    if not auto or args.snapshot_sha256:
                        # an explicit path must fail loudly; and a
                        # sha256 pin names ONE exact artifact — falling
                        # back to a different (unpinned) file would
                        # defeat the integrity gate
                        raise
                    # restart-on-failure must never crash-loop on a
                    # torn checkpoint (a kill can land inside a
                    # checkpoint commit): step back to the next-newest
                    # complete one, else start fresh
                    self._pending_snapshot, resume_src = \
                        self._auto_snapshot_fallback(snapshot, e)
                    resume_reason = (
                        "fallback: %s failed to load (%s: %s)"
                        % (snapshot, type(e).__name__, e))
            if args.snapshot:
                # the resume decision joins the flight record: a
                # post-mortem must show WHAT was restored and WHY (the
                # crashdump distance to this event is the work lost)
                from veles_tpu.telemetry import flight
                flight.record(
                    "train.resume", snapshot=resume_src,
                    reason=resume_reason,
                    epoch=None if self._pending_snapshot is None
                    else self._pending_snapshot.get("epoch"))
            if self._pending_snapshot is None and args.warm_start:
                # no (loadable) checkpoint anywhere — the fine-tuning
                # initializer applies exactly as on a fresh start
                from veles_tpu.services.snapshotter import SnapshotterBase
                self._pending_warm_start = SnapshotterBase.import_(
                    args.warm_start,
                    allow_remote=args.allow_remote_snapshot,
                    expected_sha256=args.snapshot_sha256)
            if web is not None:
                web.register(self.workflow)
            return self.workflow

        def main(**kwargs):
            wf = self.workflow
            if args.lint:
                # static analysis only: skip initialize/run entirely (no
                # XLA dispatch) — the lint itself happens after run()
                # returns, so a workflow file that never calls main()
                # still gets analyzed
                return wf
            if args.death_probability:
                wf.death_probability = args.death_probability
            launcher = self._make_launcher(args, wf)
            launcher.initialize(**kwargs)
            # graceful preemption: TPU schedulers deliver SIGTERM with a
            # grace window before the pod goes away — checkpoint at the
            # next cycle boundary and exit 75 (EX_TEMPFAIL) so the
            # deploy units' auto-restart resumes via --snapshot auto
            import signal
            import threading
            prev_term = None
            if threading.current_thread() is threading.main_thread():
                def _on_sigterm(signum, frame):
                    # flag FIRST: stderr may be mid-write when the signal
                    # lands, and a reentrant-IO RuntimeError in print()
                    # must not lose the preemption request
                    wf.request_preempt()
                    # this handler REPLACES the launcher-installed
                    # health one — keep its black box: record + dump so
                    # a pod eviction leaves the same forensics as a
                    # crash (the run itself continues to the graceful
                    # preemption checkpoint)
                    from veles_tpu.telemetry import health as _health
                    _health.note_signal("SIGTERM")
                    try:
                        print("SIGTERM: graceful preemption — "
                              "checkpointing at the next cycle, then "
                              "exit 75", file=sys.stderr, flush=True)
                    except RuntimeError:
                        pass
                prev_term = signal.signal(signal.SIGTERM, _on_sigterm)
            manhole = None
            profiling = False
            # the try opens HERE, directly after the handler install, so
            # a failure anywhere below (manhole bind, snapshot restore,
            # warm start, profiler) still restores the previous SIGTERM
            # disposition in the finally
            try:
                if args.manhole:
                    from veles_tpu.interaction import Manhole
                    manhole = Manhole(args.manhole,
                                      scope={"wf": wf, "root": root,
                                             "launcher": launcher}).start()
                if self._pending_snapshot is not None:
                    wf.restore(self._pending_snapshot)
                elif getattr(self, "_pending_warm_start", None) is not None:
                    # polymorphic like wf.restore — custom workflows can
                    # override their warm-start semantics
                    wf.warm_start(self._pending_warm_start)
                if args.profile:
                    import jax
                    jax.profiler.start_trace(args.profile)
                    profiling = True
                if args.interactive:
                    # REPL mode: the scheduler runs in a background
                    # thread so the prompt stays live with the workflow
                    # mid-training (ref the reactor thread,
                    # launcher.py:556-562).  Cleanup is DEFERRED to the
                    # REPL exit in run() — the finally below must not
                    # tear the session down under the user.
                    thread = threading.Thread(
                        name="VelesScheduler", target=launcher.run,
                        daemon=True)
                    self._interactive_session = {
                        "launcher": launcher, "thread": thread,
                        "manhole": manhole, "prev_term": prev_term,
                        "profiling": profiling, "args": args}
                    thread.start()
                    return wf
                if args.test:
                    if root.common.serve.get("use_ema", False):
                        stats = wf.evaluate(use_ema=True)
                    else:
                        # keep the zero-argument signature working for
                        # custom workflow classes that predate use_ema
                        stats = wf.evaluate()
                    print(json.dumps({"test": stats}, indent=2))
                elif args.ensemble_test:
                    stats = self._ensemble_test(wf, args)
                    print(json.dumps({"ensemble_test": stats}))
                else:
                    launcher.run()
            finally:
                if getattr(self, "_interactive_session", None) is not None:
                    pass  # deferred: _finish_interactive at REPL exit
                else:
                    if prev_term is not None:
                        import signal
                        signal.signal(signal.SIGTERM, prev_term)
                    if profiling:
                        import jax
                        jax.profiler.stop_trace()
                        print("profiler trace -> %s" % args.profile)
                    if manhole is not None:
                        manhole.stop()
                    launcher.stop()
            if args.result_file:
                wf.write_results(args.result_file)
            wf.print_stats()
            return wf

        wf_globals["run"](load, main)
        wf = self.workflow

        if args.lint:
            if wf is None:
                raise SystemExit("%s never called load(WorkflowClass, "
                                 "...) — nothing to lint" % args.workflow)
            from veles_tpu.analysis import (format_findings,
                                            lint_workflow,
                                            threshold_reached)
            if args.mesh:
                # --lint --mesh: initialize under the virtual CPU mesh
                # so the VS2xx/VM3xx sharding/memory audit can lower the
                # real staged step (params allocate; no training step
                # ever dispatches — same contract as veles-tpu-lint)
                from veles_tpu.analysis.cli import _attach_mesh
                _attach_mesh(wf, self._parse_mesh(args.mesh), args.fsdp)
            elif args.numerics or args.serve is not None:
                # --lint --numerics / --serve: same contract, no mesh —
                # both auditors need real (constructed) staged state
                from veles_tpu.analysis.cli import _initialize_plain
                _initialize_plain(wf)
            findings = lint_workflow(wf, vmem_kib=args.vmem_kib)
            if args.serve is not None:
                # VD7xx: audit the serving engine this workflow would
                # serve — abstract traces of the decode tick, no
                # decode ever dispatches
                from veles_tpu.analysis import lint_serving
                trainer = getattr(wf, "trainer", None)
                if trainer is None:
                    raise SystemExit("--lint --serve: workflow has no "
                                     ".trainer unit to build a "
                                     "serving engine from")
                findings = findings + lint_serving(
                    trainer, args.serve_max_len,
                    vmem_kib=args.vmem_kib)
            if args.concurrency or args.lint_all:
                from veles_tpu.analysis import lint_concurrency
                findings = findings + lint_concurrency()
            if args.lint_all:
                # --lint --all: every registered AST family joins the
                # workflow families in one merged report/exit gate
                # (veles-tpu-lint --all parity)
                from veles_tpu.analysis import (lint_config,
                                                lint_determinism,
                                                lint_protocol,
                                                lint_state)
                findings = (findings + lint_protocol() + lint_config()
                            + lint_state() + lint_determinism())
            print(format_findings(findings))
            return 1 if threshold_reached(findings,
                                          args.fail_on) else 0

        if self._interactive_session is not None:
            try:
                self._repl(load, main)
            finally:
                self._finish_interactive()

        if wf is not None and getattr(wf, "preempted_", False):
            # 75 = EX_TEMPFAIL: "try again" — the deploy systemd/k8s
            # units restart the identical command line and
            # --snapshot auto picks up the preemption checkpoint
            print("preempted — exiting 75 for supervisor restart",
                  file=sys.stderr, flush=True)
            return 75

        if args.export and wf is not None:
            from veles_tpu.services.export import export_workflow
            export_workflow(wf, args.export, dtype=args.export_dtype)
            print("exported -> %s" % args.export)
        if args.export_stablehlo and wf is not None:
            from veles_tpu.services.export import export_stablehlo
            meta = export_stablehlo(wf, args.export_stablehlo)
            print("stablehlo (%s) -> %s"
                  % (",".join(meta["platforms"]), args.export_stablehlo))
        if args.export_lora and wf is not None:
            from veles_tpu.services.export import export_lora_adapters
            meta = export_lora_adapters(wf, args.export_lora)
            print("lora adapters (%s) -> %s"
                  % (",".join(meta["layers"]), args.export_lora))
        if args.generate is not None and wf is not None:
            self._generate(wf, args.generate)
        if args.serve is not None and wf is not None:
            self._serve(wf, args.serve)
        return 0

    @staticmethod
    def _get_interactive_locals():
        """Workflow-construction kwargs harvested from an enclosing
        IPython session, if any (ref veles/__main__.py:380-394: the
        interactive Main feeds notebook locals into load()).  Empty dict
        outside IPython."""
        try:
            from IPython.core.getipython import get_ipython
        except ImportError:
            return {}
        shell = get_ipython()
        if shell is None:
            return {}
        return {k: v for k, v in shell.user_ns.items()
                if k[:1] != "_" and k not in
                ("In", "Out", "exit", "quit", "get_ipython", "open")}

    def _repl(self, load, main):
        """The --interactive prompt: the scheduler thread is already
        running; expose the live workflow and lifecycle helpers (ref
        Main(interactive=True) + the background reactor thread,
        veles/__main__.py:380-394, launcher.py:556-562)."""
        session = self._interactive_session
        wf = self.workflow

        def stop():
            """Stop the running workflow and join the scheduler."""
            wf.stop()
            session["thread"].join(timeout=60)
            print("scheduler %s" % ("stopped" if not
                  session["thread"].is_alive() else "STILL RUNNING"))

        def status():
            """One-line liveness + progress summary."""
            alive = session["thread"].is_alive()
            parts = ["scheduler=%s" % ("running" if alive else "done")]
            loader = getattr(wf, "loader", None)
            if loader is not None:
                parts.append("epoch=%s" % getattr(
                    loader, "epoch_number", "?"))
            dec = getattr(wf, "decision", None)
            if dec is not None and getattr(dec, "min_validation_error",
                                           None) is not None:
                parts.append("best_err=%s" % dec.min_validation_error)
            print("  ".join(parts))
            return alive

        def weights(layer=None):
            """Live parameter tree: whole dict, or one layer's params
            as numpy (safe to call mid-training — jax arrays are
            immutable snapshots)."""
            import numpy as np
            params = getattr(getattr(wf, "trainer", None), "params", {})
            if layer is None:
                return params
            return {k: np.asarray(v) for k, v in params[layer].items()}

        ns = {"wf": wf, "root": root, "load": load, "main": main,
              "launcher": session["launcher"], "stop": stop,
              "status": status, "weights": weights, "veles_main": self}
        banner = ("veles_tpu interactive — training runs in a "
                  "background thread.\n  wf        live workflow\n"
                  "  status()  scheduler/epoch/best-error\n"
                  "  weights('layer')  live params as numpy\n"
                  "  stop()    stop training and join the scheduler\n"
                  "  root      config tree     exit to leave")
        import os
        if os.environ.get("VELES_PLAIN_REPL"):
            # deterministic prompt for drivers/tests and dumb terminals
            import code
            code.interact(banner=banner, local=ns)
            return
        try:
            from IPython.terminal.embed import InteractiveShellEmbed
            InteractiveShellEmbed(banner1=banner)(local_ns=ns)
        except ImportError:
            import code
            code.interact(banner=banner, local=ns)

    def _finish_interactive(self):
        """Deferred cleanup from main()'s skipped finally: stop the
        workflow, join the scheduler thread, restore the SIGTERM
        disposition, close the profiler/manhole, stop services."""
        session, self._interactive_session = self._interactive_session, None
        wf = self.workflow
        if wf is not None:
            wf.stop()
        session["thread"].join(timeout=60)
        if session["prev_term"] is not None:
            import signal
            signal.signal(signal.SIGTERM, session["prev_term"])
        if session["profiling"]:
            import jax
            jax.profiler.stop_trace()
            print("profiler trace -> %s" % session["args"].profile)
        if session["manhole"] is not None:
            session["manhole"].stop()
        session["launcher"].stop()
        if wf is not None:
            if session["args"].result_file:
                wf.write_results(session["args"].result_file)
            wf.print_stats()

    @staticmethod
    def _make_generator(wf, min_len=0):
        """Guarded LMGenerator construction shared by --serve and
        --generate: None unless the workflow is a causal-LM stack."""
        if not any(layer.type == "transformer_block" and
                   layer.cfg.get("causal") for layer in wf.trainer.layers):
            return None
        from veles_tpu.models.generate import LMGenerator
        t0 = (wf.trainer.layers[0].input_shape[0]
              if wf.trainer.layers[0].input_shape else 0)
        if any(l.cfg.get("rope") for l in wf.trainer.layers):
            t0 = max(t0, min_len)    # rope has no position-table bound
        # root.common.serve.lora_adapters=PATH grafts a --export-lora
        # package onto the (warm-started base) workflow BEFORE the
        # generator snapshots its serving params: serve a base
        # checkpoint + a tiny adapters file instead of a full adapted
        # model (sha256 lineage enforced; ...strict=False downgrades
        # a cross-base mismatch to a warning)
        adapters = root.common.serve.get("lora_adapters", None)
        if adapters:
            from veles_tpu.services.export import apply_lora_adapters
            meta = apply_lora_adapters(
                wf, adapters,
                strict=root.common.serve.get("lora_strict", True))
            import logging
            logging.getLogger("Main").info(
                "serving with LoRA adapters %s (layers: %s)",
                adapters, ",".join(meta["layers"]))
        cd = root.common.serve.get("cache_dtype", None)
        import numpy as np
        kwargs = dict(max_len=t0, cache_dtype=None if cd is None
                      else np.dtype(cd))
        try:
            gen = LMGenerator(wf.trainer, **kwargs)
        except ValueError:
            return None              # not a generate-shaped stack
        w = root.common.serve.get("weights", None)
        use_ema = root.common.serve.get("use_ema", False)
        if w is not None or use_ema:
            # the stack IS generate-shaped (probed above) — a failure
            # here is a configuration error (bad weights value, EMA not
            # tracked, TP×int8) and must surface, not silently disable
            # generation
            gen = LMGenerator(wf.trainer, weights=w, use_ema=use_ema,
                              **kwargs)
        return gen

    def _generate(self, wf, spec):
        """--generate 'PROMPT[:MAX_NEW]' — byte-level decode from the
        trained causal LM, printed to stdout."""
        prompt, _, n = spec.rpartition(":")
        if n.strip().isdigit() and prompt:
            max_new = int(n)
        else:                        # no numeric suffix: all is prompt
            prompt, max_new = spec, 32
        toks = list(prompt.encode("utf-8"))       # true byte-level
        gen = self._make_generator(wf, min_len=len(toks) + max_new)
        if gen is None:
            raise SystemExit("--generate needs a causal transformer LM "
                             "workflow (embedding ... transformer_block "
                             "... timestep_dense)")
        if len(toks) + max_new > gen.max_len:
            raise SystemExit(
                "--generate: prompt (%d bytes) + MAX_NEW (%d) exceeds "
                "the model's position limit %d — shorten one, or train "
                "with pos='rope' (no table bound)"
                % (len(toks), max_new, gen.max_len))
        out = gen.generate([toks], max_new=max_new)
        print("generated: %r" % bytes(
            t if 0 <= t < 256 else 63 for t in out[0].tolist()
        ).decode("utf-8", errors="replace"))

    def _apply_config(self, args):
        from veles_tpu.genetics.core import Range
        if args.config:
            scope = {"root": root, "Range": Range}
            with open(args.config) as f:
                exec(compile(f.read(), args.config, "exec"), scope)
        for stmt in args.config_list:
            exec(stmt, {"root": root, "Range": Range})

    def _run_supervised(self, args):
        """``--supervise``: respawn the identical command (minus the
        flag itself, plus ``--snapshot auto``) under the supervisor's
        backoff/crash-loop policy — the Veles Launcher role collapsed
        onto one host (docs/distributed_training.md "Preemption-safe
        training")."""
        if args.snapshot not in (None, "auto"):
            raise SystemExit(
                "--supervise drives restart-on-failure through "
                "--snapshot auto; an explicit --snapshot path would "
                "re-resume the SAME file after every respawn, losing "
                "all progress between restarts")
        # config must apply in the parent too: the supervisor reads its
        # own knobs plus the snapshot/blackbox dirs from the tree
        self._apply_config(args)
        from veles_tpu.services.supervisor import Supervisor
        child = [a for a in self.argv if a != "--supervise"]
        if args.snapshot is None:
            child += ["--snapshot", "auto"]
        if args.snapshot_every is None:
            print("[supervise] note: no --snapshot-every — unless the "
                  "workflow wires its own snapshotter, respawns will "
                  "restart from scratch", file=sys.stderr)
        # progress is watched on the CONFIG-TREE snapshot dir: a
        # workflow whose snapshotter_config names a different explicit
        # 'directory' still restarts correctly, but checkpoint commits
        # there won't reset the backoff/deterministic-bug counters —
        # point root.common.dirs.snapshots at it to get both
        sup = Supervisor(
            [sys.executable, "-m", "veles_tpu"] + child,
            blackbox_dir=root.common.blackbox.get("dir", "artifacts"),
            progress_paths=[root.common.dirs.get("snapshots",
                                                 "snapshots")])
        return sup.run()

    @staticmethod
    def _auto_snapshot_fallback(current, error):
        """--snapshot auto hit a torn/unloadable checkpoint: quarantine
        it (rename to ``*.corrupt`` so restarts stop re-trying it),
        then try the other snapshots of the same prefix, newest first;
        ``(None, None)`` (fresh start) when none load.  A supervisor
        restart loop must converge to training, never to a crash loop.

        :returns: ``(snapshot_dict_or_None, loaded_path_or_None)``."""
        import os

        from veles_tpu.services.snapshotter import (MANIFEST_SUFFIX,
                                                    SnapshotterBase)
        real = os.path.realpath(current)
        directory = os.path.dirname(real)
        prefix = os.path.basename(current).replace("_current", "")
        print("[auto-resume] %s failed to load (%s) — trying older "
              "checkpoints" % (real, error), file=sys.stderr)
        # quarantine only CORRUPTION-class failures: a transient
        # OSError (shared-storage hiccup) must not permanently demote
        # the newest good checkpoint — the next restart retries it
        if not isinstance(error, OSError) and os.path.exists(real):
            q = SnapshotterBase.quarantine(real)
            if q:
                print("[auto-resume] quarantined torn checkpoint -> %s"
                      % q, file=sys.stderr)
        candidates = sorted(
            (os.path.join(directory, n) for n in os.listdir(directory)
             # prefix + "_": the filename format is "<prefix>_<suffix>"
             # — a bare startswith would also match a DIFFERENT
             # workflow ("digits-mlp-big") sharing the snapshot dir
             if n.startswith(prefix + "_")
             and not n.endswith("_current")
             and not n.endswith(MANIFEST_SUFFIX)
             and not n.endswith(".corrupt")
             # .tmp leftovers are UNCOMMITTED writes (a kill between
             # dump and rename): a complete-looking one would resume
             # manifest-less past the integrity gate
             and ".tmp" not in n
             and os.path.join(directory, n) != real),
            key=os.path.getmtime, reverse=True)
        for cand in candidates:
            try:
                snap = SnapshotterBase.import_(cand)
            except Exception as e:  # noqa: BLE001 — keep stepping back
                print("[auto-resume] %s also failed (%s)" % (cand, e),
                      file=sys.stderr)
                if not isinstance(e, OSError):
                    q = SnapshotterBase.quarantine(cand)
                    if q:
                        print("[auto-resume] quarantined -> %s" % q,
                              file=sys.stderr)
                continue
            print("[auto-resume] recovered from %s" % cand,
                  file=sys.stderr)
            return snap, cand
        print("[auto-resume] no loadable checkpoint — fresh start",
              file=sys.stderr)
        return None, None

    @staticmethod
    def _resolve_auto_snapshot(wf):
        """--snapshot auto: follow <prefix>_current in the snapshot dir;
        absent → fresh start (so the same command line both starts and
        resumes a run — ref respawn semantics, veles/server.py:637-655
        mapped to checkpoint-restart)."""
        import os
        snap = getattr(wf, "snapshotter", None)
        directory = (snap.directory if snap is not None
                     else root.common.dirs.get("snapshots", "snapshots"))
        prefix = snap.prefix if snap is not None else wf.name
        current = os.path.join(directory, "%s_current" % prefix)
        if os.path.islink(current) and not os.path.exists(current):
            # dangling symlink (target deleted/renamed/never finalized):
            # used to silently read as "no checkpoint" and fresh-start
            # over real progress — surface it and let the fallback scan
            # pick the newest valid checkpoint instead
            try:
                target = os.readlink(current)
            except OSError:
                target = "?"
            print("[auto-resume] %s dangles (target %s is missing) — "
                  "falling back to the newest valid checkpoint"
                  % (current, target), file=sys.stderr)
            return current   # import_ fails -> _auto_snapshot_fallback
        if os.path.exists(current):
            print("[auto-resume] %s" % os.path.realpath(current),
                  file=sys.stderr)
            return current
        print("[auto-resume] no %s — fresh start" % current,
              file=sys.stderr)
        return None

    # ------------------------------------------------------------- launcher
    @staticmethod
    def _parse_mesh(spec):
        """'data=4,model=2' -> {'data': 4, 'model': 2} (ref device-spec
        grammar backends.py:299-308 / launcher -n node specs; 'DxM'
        shorthand also accepted — one parser, analysis.cli.parse_mesh)."""
        if not spec:
            return None
        from veles_tpu.analysis.cli import parse_mesh
        return parse_mesh(spec)

    def _make_launcher(self, args, wf):
        from veles_tpu.launcher import Launcher
        self.launcher = Launcher(
            workflow=wf, mesh_axes=self._parse_mesh(args.mesh),
            coordinator_address=args.coordinator,
            num_processes=args.num_processes, process_id=args.process_id,
            fsdp=args.fsdp)
        return self.launcher

    # -------------------------------------------------- meta: genetics / GA
    @staticmethod
    def _child_argv(args, extra_config, extra_flags, workers=1):
        """argv for a child training run: rebuilt from the parsed parent
        args (workflow/config/config-list/backend carry over; meta flags
        do not — ref Launcher.filter_argv forwarding, launcher.py:75).
        With concurrent workers the children are pinned to cpu HERE too:
        a forwarded --backend would override the JAX_PLATFORMS env pin
        inside the child and put N children on one accelerator."""
        argv = [sys.executable, "-m", "veles_tpu", args.workflow]
        if args.config:
            argv.append(args.config)
        config_list = list(args.config_list) + list(extra_config)
        if config_list:
            argv += ["--config-list"] + config_list
        if workers > 1:
            argv += ["--backend", "cpu"]
        elif args.backend:
            argv += ["--backend", args.backend]
        return argv + list(extra_flags)

    @staticmethod
    def _child_env(workers):
        import os
        env = dict(os.environ)
        if workers > 1:
            # concurrent children must not fight over one accelerator
            env["JAX_PLATFORMS"] = "cpu"
        return env

    #: watchdog for each child training run — a wedged backend must fail
    #: the evaluation, not hang the whole GA/ensemble (same reasoning as
    #: bench.py's per-phase watchdogs)
    @staticmethod
    def _child_timeout():
        import os
        return float(os.environ.get("VELES_TPU_CHILD_TIMEOUT", 1800))

    @staticmethod
    def _executor_map(workers):
        """Parallel map over training *subprocesses* (one eval per process
        — ref distributed GA fitness, genetics/optimization_workflow.py:
        181-216; threads only marshal argv/JSON, the work is in the
        children)."""
        if workers <= 1:
            return lambda f, xs: list(map(f, xs))

        def pmap(f, xs):
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=workers) as pool:
                return list(pool.map(f, xs))
        return pmap

    def _evaluate_leaves(self, args, leaves, workers):
        """ONE fitness evaluation: full training subprocess with the
        chromosome's {dotted-path: value} overrides; --result-file
        best_metric (lower is better) becomes -fitness.  Shared by the
        local GA executor and the --optimize-worker loop."""
        import subprocess
        import tempfile

        overrides = ["root.%s=%r" % (p, v) for p, v in leaves.items()]
        seed_flags = ([] if args.random_seed is None
                      else ["--random-seed", str(args.random_seed)])
        with tempfile.NamedTemporaryFile("r", suffix=".json") as tmp:
            argv = self._child_argv(
                args, overrides,
                ["--result-file", tmp.name] + seed_flags,
                workers=workers)
            try:
                r = subprocess.run(
                    argv, capture_output=True, text=True,
                    timeout=self._child_timeout(),
                    env=self._child_env(workers))
            except subprocess.TimeoutExpired:
                print("[optimize] evaluation timed out", file=sys.stderr)
                return float("-inf")
            if r.returncode != 0:
                print("[optimize] evaluation failed: %s"
                      % r.stderr[-500:], file=sys.stderr)
                return float("-inf")
            metric = json.load(open(tmp.name)).get("best_metric")
        return float("-inf") if metric is None else -float(metric)

    @staticmethod
    def _parse_optimize_workers(spec):
        """'N' -> (N, None); 'N@HOST:PORT' -> (N, 'HOST:PORT')."""
        head, _, addr = str(spec).partition("@")
        try:
            n = int(head)
            if addr:
                int(addr.rpartition(":")[2])   # PORT must be numeric
        except ValueError:
            raise SystemExit("--optimize-workers: expected N or "
                             "N@HOST:PORT, got %r" % (spec,))
        return n, (addr or None)

    def _run_optimize_worker(self, args):
        """The slave side of the GA job protocol: pull chromosome jobs
        from the coordinator's queue, train locally, post fitness."""
        from veles_tpu.genetics.distributed import run_worker

        count = run_worker(
            args.optimize_worker,
            lambda leaves: self._evaluate_leaves(args, leaves, workers=1))
        print(json.dumps({"optimize_worker": {"evaluated": count}}))
        return 0

    def _run_optimize(self, args):
        """--optimize SIZE[:GENS] (ref veles/__main__.py:334-345): GA over
        every Range() leaf in the config tree; each fitness evaluation is
        a full training subprocess whose --result-file best_metric (lower
        is better) becomes -fitness.  With --optimize-workers N@HOST:PORT
        the evaluations additionally spread over remote --optimize-worker
        processes (ref distributed fitness,
        genetics/optimization_workflow.py:181-216)."""
        from veles_tpu.genetics.core import extract_ranges
        from veles_tpu.genetics.optimizer import GeneticsOptimizer

        n_workers, queue_addr = self._parse_optimize_workers(
            args.optimize_workers)
        head, _, tail = args.optimize.partition(":")
        size, generations = int(head), int(tail) if tail else 10
        cfg = root.as_dict()
        paths = extract_ranges(cfg)
        if not paths:
            raise SystemExit("--optimize: no Range() leaves in the config "
                             "tree — tag tunables like "
                             "root.x.lr = Range(0.01, 1.0)")

        def leaf(tree, path):
            for k in path:
                tree = tree[k]
            return tree

        def evaluate(config):
            return self._evaluate_leaves(
                args, {".".join(p): leaf(config, p) for p, _ in paths},
                workers=n_workers)

        queue = None
        executor_map = self._executor_map(n_workers)
        if queue_addr:
            from veles_tpu.genetics.distributed import FitnessQueue
            host, _, port = queue_addr.rpartition(":")
            # lease > child watchdog + margin: an evaluation that itself
            # times out must post its -inf BEFORE the lease expires, or
            # a second worker redundantly re-runs the doomed config
            queue = FitnessQueue(host or "0.0.0.0", int(port or 0),
                                 job_timeout=self._child_timeout() + 120)
            queue.start()
            print("[optimize] serving chromosome queue on %s:%d — "
                  "workers join with --optimize-worker HOST:%d"
                  % (queue.host, queue.port, queue.port),
                  file=sys.stderr)

            def executor_map(f, configs):  # noqa: F811 — queue mode
                return queue.map(
                    lambda leaves: self._evaluate_leaves(
                        args, leaves, workers=max(n_workers, 1)),
                    [{".".join(p): leaf(c, p) for p, _ in paths}
                     for c in configs],
                    local_workers=n_workers)

        try:
            opt = GeneticsOptimizer(
                cfg, evaluate, size=size, generations=generations,
                encoding=args.optimize_encoding,
                executor_map=executor_map)
            best = opt.run()
        finally:
            if queue is not None:
                queue.shutdown()
                # give polling workers a beat to read the done signal
                import time as _time
                _time.sleep(1.5)
                queue.stop()
        if opt.population.best.fitness == float("-inf"):
            print("--optimize: every fitness evaluation failed — no "
                  "usable result", file=sys.stderr)
            return 1
        result = {
            "optimize": {
                "best_config": {"root." + ".".join(p): leaf(best, p)
                                for p, _ in paths},
                "best_fitness": opt.population.best.fitness,
                "history": opt.history,
            }
        }
        print(json.dumps(result, indent=2))
        if args.result_file:
            with open(args.result_file, "w") as f:
                json.dump(result, f, indent=2)
        return 0

    # ------------------------------------------------------------ ensembles
    def _run_ensemble_train(self, args):
        """--ensemble-train N:RATIO (ref ensemble/model_workflow.py:137):
        N training subprocesses, each on a random train subset of RATIO
        and its own seed, each exporting a model package; aggregated
        results JSON feeds --ensemble-test."""
        import os
        import subprocess

        head, _, tail = args.ensemble_train.partition(":")
        n_models, ratio = int(head), float(tail) if tail else 0.8
        out_file = args.result_file or "ensemble_results.json"
        out_dir = os.path.abspath(
            os.path.splitext(out_file)[0] + "_members")
        os.makedirs(out_dir, exist_ok=True)

        def train_member(i):
            res = os.path.join(out_dir, "member_%02d.json" % i)
            pkg = os.path.join(out_dir, "member_%02d.zip" % i)
            seed = 1000 + i
            argv = self._child_argv(
                args,
                ["root.common.ensemble.instance=%d" % i,
                 "root.common.ensemble.train_ratio=%r" % ratio],
                ["--random-seed", str(seed),
                 "--result-file", res, "--export", pkg],
                workers=args.ensemble_workers)
            try:
                r = subprocess.run(
                    argv, capture_output=True, text=True,
                    timeout=self._child_timeout(),
                    env=self._child_env(args.ensemble_workers))
            except subprocess.TimeoutExpired:
                return {"instance": i, "seed": seed,
                        "error": "training timed out"}
            if r.returncode != 0:
                return {"instance": i, "seed": seed,
                        "error": r.stderr[-500:]}
            member = {"instance": i, "seed": seed, "package": pkg,
                      "train_ratio": ratio}
            member["result"] = json.load(open(res))
            return member

        members = self._executor_map(args.ensemble_workers)(
            train_member, range(n_models))
        failed = [m for m in members if "error" in m]
        result = {"members": members, "n_models": n_models,
                  "train_ratio": ratio}
        with open(out_file, "w") as f:
            json.dump(result, f, indent=2)
        print(json.dumps({"ensemble_train": {
            "n_models": n_models, "failed": len(failed),
            "results_file": out_file}}, indent=2))
        return 1 if failed else 0

    def _ensemble_test(self, wf, args):
        """Mean-probability vote over the members' exported packages on
        the workflow's eval samples (ref EnsembleTestWorkflow averaging,
        ensemble/test_workflow.py:102)."""
        import numpy as np

        from veles_tpu.loader.base import TEST, VALID
        from veles_tpu.services.export import import_workflow

        loader = wf.loader
        if loader.carries_data:
            raise SystemExit("--ensemble-test needs an index loader with "
                             "an HBM/host-resident eval set")
        from veles_tpu.ops.losses import get_loss
        if get_loss(wf.trainer.loss)[1] != "class" or loader.labels is None:
            raise SystemExit("--ensemble-test is a mean-probability vote — "
                             "it needs a classification workflow with "
                             "labels")
        members = json.load(open(args.ensemble_test))["members"]
        members = [m for m in members if "package" in m]
        if not members:
            raise SystemExit("no successfully trained members in %s"
                             % args.ensemble_test)
        # eval span: validation if present, else test
        cls = VALID if loader.class_lengths[VALID] else TEST
        start = 0 if cls == TEST else loader.class_offsets[TEST]
        end = loader.class_offsets[cls]
        if end == start:
            raise SystemExit("--ensemble-test: the loader has no "
                             "test/validation samples to vote on")
        x = np.asarray(loader.data)[start:end]
        labels = np.asarray(loader.labels)[start:end]
        fwd = wf.forward_fn()
        probs = None
        for m in members:
            manifest, arrays = import_workflow(m["package"])
            from veles_tpu.services.export import unflatten_params
            params = {
                u["name"]: unflatten_params(
                    {p: arrays[f] for p, f in u["arrays"].items()})
                for u in manifest["units"] if u["arrays"]}
            p = np.asarray(fwd(params, x))
            probs = p if probs is None else probs + p
        pred = (probs / len(members)).argmax(axis=1)
        error = float((pred != labels).mean())
        return {"n_members": len(members), "n_samples": int(end - start),
                "error": error}

    def _serve(self, wf, port):
        import numpy as np

        from veles_tpu.services.restful import RESTfulAPI
        fwd = wf.forward_fn()
        # root.common.serve.use_ema=True serves the Polyak/EMA-averaged
        # weights (train with gd_defaults={'ema_decay': ...})
        params = wf.trainer.serve_params(
            root.common.serve.get("use_ema", False))
        # root.common.serve.cache_dtype='bfloat16' halves the serve-time
        # KV-cache memory ('int8' quarters it — and feeds the fused
        # paged decode kernel's quantized-pool variant);
        # root.common.serve.weights='int8' quantizes the serving weights
        # (W8A8-dynamic, ops.quant) for ~half the decode HBM traffic,
        # 'w4a8' nibble-packs them to int4 payloads (quarter bytes);
        # root.common.serve.batch_window_ms>0 coalesces concurrent
        # generate requests into shared device calls;
        # root.common.serve.continuous_slots>0 runs the in-flight
        # continuous-batching engine instead (docs/services.md)
        api = RESTfulAPI(lambda x: np.asarray(fwd(params, x)),
                         wf.trainer.layers[0].input_shape, port=port,
                         generator=self._make_generator(wf),
                         batch_window=float(
                             root.common.serve.get("batch_window_ms", 0))
                         / 1e3,
                         max_batch=int(
                             root.common.serve.get("max_batch", 8)),
                         continuous_slots=int(
                             root.common.serve.get("continuous_slots",
                                                   0)),
                         # root.common.serve.paged_block>0: block-table
                         # KV pool of root.common.serve.pool_tokens —
                         # memory scales with active tokens, admission
                         # backpressures on pool exhaustion; "auto"/-1
                         # = paged with the pool block resolved through
                         # config > the kernel autotuner > default
                         # (generate.parse_paged_block grammar)
                         paged_block=root.common.serve.get(
                             "paged_block", 0),
                         pool_tokens=root.common.serve.get(
                             "pool_tokens", None),
                         # prefix_cache: concurrent requests sharing a
                         # prompt prefix share its KV blocks (the
                         # system-prompt case pays for it once)
                         prefix_cache=bool(root.common.serve.get(
                             "prefix_cache", False)),
                         # speculative_k>0: n-gram speculative ticks in
                         # the dense slot pool (exact decode semantics)
                         speculative_k=int(root.common.serve.get(
                             "speculative_k", 0)),
                         # K>1 fuses K engine ticks per device dispatch
                         # (remote/tunneled devices: the round trip
                         # dominates per-token cost)
                         ticks_per_dispatch=int(root.common.serve.get(
                             "ticks_per_dispatch", 1)))
        # root.common.serve.prefill_segment>0: segmented prefill
        # admission — long prompts prefill in bounded chunk passes
        # interleaved with decode ticks, so one admission can't stall
        # every in-flight stream (the engine reads the knob itself;
        # docs/services.md "Disaggregated prefill")
        api.start()
        if getattr(self, "_web", None) is not None:
            # the dashboard's serving panel shows the slot pool's SLO
            # surface (queue depth, p50/p99 latency) live
            self._web.register_serving(api)
        # SIGTERM (preemption, `kill`, a fleet operator) drains
        # gracefully: stop admission (503 + Retry-After), finish every
        # in-flight request, exit 0 — the same lifecycle a fleet
        # replica walks (docs/services.md "Fleet serving"), instead of
        # the training path's crashdump-and-die
        from veles_tpu.services.restful import (announce_ready,
                                                install_sigterm_drain)
        install_sigterm_drain(api)
        # under a pod agent (VELES_TPU_REPLICA_ANNOUNCE set) this
        # prints the fleet READY handshake so the agent can register
        # the bound port with the router — any --serve command is a
        # fleet replica (docs/services.md "Autoscaling fleet")
        announce_ready(api)
        print("REST serving on port %d; Ctrl-C to stop, SIGTERM to "
              "drain" % api.port)
        try:
            import time
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            api.stop()


def __run__():
    argv = sys.argv[1:]
    if argv and argv[0] == "--tune":
        # kernel-autotuner surface (no workflow involved):
        # `python -m veles_tpu --tune sweep ...` == `veles-tpu-tune
        # sweep ...` — sweep/list/clear the winner cache (docs/cli.md)
        from veles_tpu.tuner.cli import main as tune_main
        sys.exit(tune_main(argv[1:]))
    sys.exit(Main().run())


if __name__ == "__main__":
    __run__()
