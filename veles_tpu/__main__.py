"""CLI entry point (ref: veles/__main__.py — `python -m veles_tpu
workflow.py [config.py]`).

Keeps the reference's module contract (__main__.py:799-818): the workflow
file defines ``run(load, main)`` and calls
``load(WorkflowClass, **kwargs)`` to construct (or snapshot-resume) the
workflow, then ``main(**kwargs)`` to initialize + run it.  Config files
are Python executed with ``root`` in scope, mutating the global config
tree (ref _apply_config, __main__.py:426-472); ``--config-list`` inline
statements layer on top."""

import argparse
import json
import runpy
import sys

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.logger import setup_logging


class Main(object):
    def __init__(self, argv=None):
        self.argv = argv if argv is not None else sys.argv[1:]
        self.workflow = None

    def parse(self):
        return self._build_parser().parse_args(self.argv)

    def _build_parser(self):
        """The full CLI parser (also consumed by
        scripts.generate_frontend to emit the HTML command composer)."""
        p = argparse.ArgumentParser(
            prog="veles_tpu",
            description="TPU-native deep-learning platform")
        p.add_argument("workflow", help="workflow .py file defining "
                       "run(load, main)")
        p.add_argument("config", nargs="?", help="config .py file "
                       "executed with `root` in scope")
        p.add_argument("--config-list", nargs="*", default=[],
                       help="inline config statements, e.g. "
                       "'root.mnist.lr=0.1'")
        p.add_argument("--random-seed", type=int, default=None)
        p.add_argument("--snapshot", default=None,
                       help="resume from a snapshot file")
        p.add_argument("--allow-remote-snapshot", action="store_true",
                       help="opt in to importing --snapshot from an "
                       "http(s) URL (pickle import runs code)")
        p.add_argument("--snapshot-sha256", default=None,
                       help="expected sha256 of a remote --snapshot")
        p.add_argument("--test", action="store_true",
                       help="skip training; run forward on the loader's "
                       "test/validation set")
        p.add_argument("--result-file", default=None,
                       help="write gather_results() JSON here")
        p.add_argument("--export", default=None,
                       help="export trained model package to this path")
        p.add_argument("--serve", type=int, default=None, metavar="PORT",
                       help="after training, serve the model over REST")
        p.add_argument("--web-status", type=int, default=None,
                       metavar="PORT", help="launch the status dashboard")
        p.add_argument("--backend", default=None,
                       help="cpu|tpu|<platform> override")
        p.add_argument("--profile", default=None, metavar="DIR",
                       help="capture a jax/xplane profiler trace of the "
                       "run into DIR (view with tensorboard or xprof; "
                       "the TPU equivalent of the reference's per-unit "
                       "timing + event timeline)")
        p.add_argument("--verbose", "-v", action="count", default=0)
        return p

    def run(self):
        args = self.parse()
        import logging
        setup_logging(logging.DEBUG if args.verbose else logging.INFO)
        if args.backend:
            import jax
            jax.config.update(
                "jax_platforms",
                "cpu" if args.backend == "cpu" else args.backend)
        if args.random_seed is not None:
            prng.seed_all(args.random_seed)
        self._apply_config(args)

        web = None
        if args.web_status is not None:
            from veles_tpu.services.web_status import WebStatusServer
            web = WebStatusServer(port=args.web_status)
            web.start()

        wf_globals = runpy.run_path(args.workflow, run_name="__veles__")
        if "run" not in wf_globals:
            raise SystemExit("%s does not define run(load, main)"
                             % args.workflow)

        def load(cls, **kwargs):
            self.workflow = cls(**kwargs)
            if args.snapshot:
                from veles_tpu.services.snapshotter import SnapshotterBase
                # initialize first so staged steps exist, then restore
                self._pending_snapshot = SnapshotterBase.import_(
                    args.snapshot,
                    allow_remote=args.allow_remote_snapshot,
                    expected_sha256=args.snapshot_sha256)
            else:
                self._pending_snapshot = None
            if web is not None:
                web.register(self.workflow)
            return self.workflow

        def main(**kwargs):
            wf = self.workflow
            wf.initialize(**kwargs)
            if self._pending_snapshot is not None:
                wf.restore(self._pending_snapshot)
            profiling = False
            if args.profile:
                import jax
                jax.profiler.start_trace(args.profile)
                profiling = True
            try:
                if args.test:
                    stats = wf.evaluate()
                    print(json.dumps({"test": stats}, indent=2))
                else:
                    wf.run()
            finally:
                if profiling:
                    import jax
                    jax.profiler.stop_trace()
                    print("profiler trace -> %s" % args.profile)
            if args.result_file:
                wf.write_results(args.result_file)
            wf.print_stats()
            return wf

        wf_globals["run"](load, main)
        wf = self.workflow

        if args.export and wf is not None:
            from veles_tpu.services.export import export_workflow
            export_workflow(wf, args.export)
            print("exported -> %s" % args.export)
        if args.serve is not None and wf is not None:
            self._serve(wf, args.serve)
        return 0

    def _apply_config(self, args):
        if args.config:
            scope = {"root": root}
            with open(args.config) as f:
                exec(compile(f.read(), args.config, "exec"), scope)
        for stmt in args.config_list:
            exec(stmt, {"root": root})

    def _serve(self, wf, port):
        import numpy as np

        from veles_tpu.services.restful import RESTfulAPI
        fwd = wf.forward_fn()
        params = wf.trainer.params
        api = RESTfulAPI(lambda x: np.asarray(fwd(params, x)),
                         wf.trainer.layers[0].input_shape, port=port)
        api.start()
        print("REST serving on port %d; Ctrl-C to stop" % api.port)
        try:
            import time
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            api.stop()


def __run__():
    sys.exit(Main().run())


if __name__ == "__main__":
    __run__()
