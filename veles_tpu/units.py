"""Unit — a node in the workflow control/data graph (ref: veles/units.py).

A Unit mirrors the reference's contract: control links (``link_from``) decide
*when* it runs, gated by lazy :class:`~veles_tpu.mutable.Bool` conditions
(``gate_block`` / ``gate_skip`` / ``ignores_gate``, ref units.py:139-141);
data links (``link_attrs``, ref units.py:638) forward attributes from producer
units; ``demand()`` (ref units.py:682) declares attributes that must be
present before ``initialize()``.

Execution-model departure from the reference (deliberate, TPU-first): the
reference fans every ``run_dependent`` out to a Twisted thread pool
(units.py:485-505) because each unit dispatches its own device kernel.  Here
the per-iteration compute of a workflow is staged into a single jitted XLA
step (see :mod:`veles_tpu.workflow`), so the host graph walk is cheap and runs
on a single-threaded queue scheduler — which deletes the reference's whole
locking surface (``_run_lock_``/``_gate_lock_``/deadlock watchdog,
SURVEY.md §5 "race detection")."""

import time

from veles_tpu import telemetry
from veles_tpu.logger import Logger
from veles_tpu.mutable import Bool
from veles_tpu.registry import UnitRegistry


class Unit(Logger, metaclass=UnitRegistry):
    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(Unit, self).__init__(**kwargs)
        self.name = kwargs.get("name", type(self).__name__)
        #: control links: predecessor Unit -> fired flag (plain bool)
        self.links_from = {}
        #: control links: successor Units (set)
        self.links_to = set()
        self.gate_block = Bool(False)   # don't run, don't propagate
        self.gate_skip = Bool(False)    # don't run, do propagate
        #: run as soon as ANY predecessor fires (Repeater-style loop closers)
        self.ignores_gate = Bool(False)
        self._linked_attrs_ = {}
        self._demanded_ = set()
        self._initialized = False
        #: per-unit span aggregate (telemetry): count/total/min/max/last
        #: seconds inside run() — the structured replacement for the old
        #: run_count/run_time counters, which remain as compatibility
        #: properties over it
        self.span = telemetry.SpanAggregate("unit.run")
        #: per-call duration prints: per-unit ``timings=True`` kwarg or
        #: the global ``root.common.timings`` (ref units.py:144-149)
        if "timings" in kwargs:
            self.timings = bool(kwargs["timings"])
        else:
            from veles_tpu.config import root
            self.timings = bool(root.common.get("timings", False))
        self.view_group = kwargs.get("view_group", "PLUMBING")
        self.workflow = workflow
        if workflow is not None:
            workflow.add_ref(self)

    # ------------------------------------------------------------------ repr
    def __repr__(self):
        return "<%s %r>" % (type(self).__name__, self.name)

    # -------------------------------------------------- span compatibility
    @property
    def run_count(self):
        """Times run() has executed (compatibility view of ``span``)."""
        return self.span.count

    @run_count.setter
    def run_count(self, value):
        self.span.count = int(value)

    @property
    def run_time(self):
        """Total seconds inside run() (compatibility view of ``span``)."""
        return self.span.total

    @run_time.setter
    def run_time(self, value):
        self.span.total = float(value)

    # -------------------------------------------------------- control links
    def link_from(self, *units):
        """Run after ``units`` (all of them, unless ``ignores_gate``).
        Ref units.py:554."""
        for u in units:
            self.links_from[u] = False
            u.links_to.add(self)
        return self

    def unlink_from(self, *units):
        for u in units:
            self.links_from.pop(u, None)
            u.links_to.discard(self)
        return self

    def unlink_all(self):
        # the pops/discards inside unlink_from are unconditional, so even
        # one-sided entries left by direct links_from/links_to surgery
        # come out — both tables are empty afterwards (the postcondition
        # the analysis linter's dangling-link rule relies on)
        for u in list(self.links_from):
            self.unlink_from(u)
        for u in list(self.links_to):
            u.unlink_from(self)
        return self

    def open_gate(self, src):
        """Mark the control link from ``src`` fired; return True when the
        gate opens (all links fired, or ``ignores_gate``).  Ref units.py:524."""
        if src in self.links_from:
            self.links_from[src] = True
        if bool(self.ignores_gate):
            return True
        if all(self.links_from.values()):
            return True
        return False

    def reset_gate(self):
        for u in self.links_from:
            self.links_from[u] = False

    # ----------------------------------------------------------- data links
    @property
    def linked_attrs(self):
        """Read-only view of the data-link table:
        ``{my_name: (source_unit, source_name, two_way)}``.  Introspection
        surface for the static analyzer (veles_tpu.analysis) — mutate via
        :meth:`link_attrs` / :meth:`unlink_attrs`, never through this."""
        return dict(self._linked_attrs_)

    def unlink_attrs(self, *names):
        """Drop data links by local name (all of them when called with no
        names).  The inverse of :meth:`link_attrs`."""
        for name in (names or list(self._linked_attrs_)):
            self._linked_attrs_.pop(name, None)
        return self

    def link_attrs(self, other, *names, two_way=False):
        """Forward attributes from ``other`` (ref units.py:638).

        Each name is either ``"attr"`` (same name both sides) or a tuple
        ``("my_name", "other_name")``.  Reads of ``self.my_name`` resolve to
        ``other.other_name`` live; writes raise unless ``two_way``."""
        for name in names:
            if isinstance(name, tuple):
                mine, theirs = name
            else:
                mine = theirs = name
            if mine in self.__dict__:
                del self.__dict__[mine]
            self._linked_attrs_[mine] = (other, theirs, two_way)
        return self

    def __getattr__(self, name):
        # only called when normal lookup fails
        links = self.__dict__.get("_linked_attrs_")
        if links and name in links:
            src, theirs, _ = links[name]
            return getattr(src, theirs)
        raise AttributeError("%s has no attribute %r" % (self, name))

    def __setattr__(self, name, value):
        links = self.__dict__.get("_linked_attrs_")
        if links and name in links:
            src, theirs, two_way = links[name]
            if not two_way:
                raise AttributeError(
                    "%r is linked one-way from %s.%s" % (name, src, theirs))
            setattr(src, theirs, value)
            return
        object.__setattr__(self, name, value)

    # --------------------------------------------------------------- demand
    def demand(self, *names):
        """Declare attributes that must be non-None before initialize()
        (ref units.py:682)."""
        self._demanded_.update(names)

    def verify_demands(self):
        """A demand is satisfied by an established data link (the producer may
        only materialize the value at run time — the Loader pattern) or by a
        non-None attribute."""
        missing = [n for n in self._demanded_
                   if n not in self._linked_attrs_
                   and getattr(self, n, None) is None]
        if missing:
            raise MissingDemands(self, missing)

    # ------------------------------------------------------------ lifecycle
    def initialize(self, **kwargs):
        """Override; called in dependency order by Workflow.initialize."""
        pass

    def run(self):
        """Override; one hot-loop step."""
        pass

    def stop(self):
        """Called on workflow stop for units holding external resources."""
        pass

    # called by the Workflow scheduler
    def _initialize_wrapped(self, **kwargs):
        self.verify_demands()
        result = self.initialize(**kwargs)
        self._initialized = True
        return result

    def _run_wrapped(self):
        # host span doubling as a device-trace annotation: an xplane
        # capture shows "unit.run:<name>" against the TPU timeline under
        # the same name the host-side aggregates use
        ann = telemetry.trace_annotation()
        t0 = time.perf_counter()
        if ann is not None:
            with ann("unit.run:%s" % self.name):
                self.run()
        else:
            self.run()
        dt = time.perf_counter() - t0
        self.span.add(dt)
        if self.timings:
            # per-call duration print (ref units.py:144-149: per-unit
            # timings=True kwarg or the global root.common.timings)
            self.debug("run #%d: %.3f ms", self.run_count, dt * 1e3)
        return dt


class MissingDemands(AttributeError):
    """Raised when demanded attributes are absent — Workflow.initialize
    treats it as "requeue this unit" (ref workflow.py:299-345)."""

    def __init__(self, unit, names):
        super(MissingDemands, self).__init__(
            "%s demands unset attributes: %s" % (unit, ", ".join(names)))
        self.unit = unit
        self.names = names


class TrivialUnit(Unit):
    """Concrete no-op Unit (ref units.py:917)."""

    def run(self):
        pass


class Container(Unit):
    """A Unit that contains other Units (Workflow base, ref units.py:925)."""
    hide_from_registry = True
