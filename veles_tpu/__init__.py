"""veles_tpu — a TPU-native deep-learning platform with the capability surface of
Samsung Veles (reference: /root/reference, see SURVEY.md).

A model is a Workflow: a directed graph of Units connected by control links (who
runs after whom, gated by lazy ``Bool`` conditions) and data links (shared
attributes).  Unlike the reference — which dispatches one OpenCL/CUDA kernel per
unit per iteration from a thread pool — the hot loop here is *staged*: the
forward/backward/update chain of a workflow is compiled into a single jitted XLA
step function over a ``jax.sharding.Mesh``, so the same workflow runs standalone
on one chip or SPMD data/tensor-parallel across a pod (the reference's ZeroMQ
master–slave role, `veles/server.py`/`client.py`, is played by ICI collectives).

Public surface (mirrors reference layers, SURVEY.md §1):
  veles_tpu.config     — auto-vivifying ``root`` config tree   (ref veles/config.py)
  veles_tpu.logger     — class-scoped logging + event records  (ref veles/logger.py)
  veles_tpu.mutable    — lazy Bool gates, LinkableAttribute    (ref veles/mutable.py)
  veles_tpu.registry   — unit/mapped registries                (ref veles/unit_registry.py)
  veles_tpu.prng       — reproducible named key streams        (ref veles/prng/)
  veles_tpu.units      — Unit, control/data links, gates       (ref veles/units.py)
  veles_tpu.workflow   — Workflow container + staging compiler (ref veles/workflow.py)
  veles_tpu.ops        — pure-jax compute library              (ref ocl/*.cl, cuda/*.cu, znicz)
  veles_tpu.models     — layers, GD, StandardWorkflow, Kohonen (ref veles/znicz docs)
  veles_tpu.loader     — minibatch serving state machine       (ref veles/loader/)
  veles_tpu.parallel   — mesh, sharding rules, collectives     (ref veles/server.py+client.py)
  veles_tpu.services   — snapshotter, results, plotting, REST  (ref veles/snapshotter.py etc.)
"""

__version__ = "0.3.0"
__root__ = __name__
